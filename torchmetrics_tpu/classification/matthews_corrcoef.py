"""Matthews correlation coefficient metric classes (reference: classification/matthews_corrcoef.py)."""

from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.matthews_corrcoef import _matthews_corrcoef_reduce


class BinaryMatthewsCorrCoef(BinaryConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold=threshold, normalize=None, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)

    def _compute(self, state: State):
        return _matthews_corrcoef_reduce(state["confmat"])


class MulticlassMatthewsCorrCoef(MulticlassConfusionMatrix):
    """Matthews correlation from the confusion matrix (reference classification/matthews_corrcoef.py:95).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import MulticlassMatthewsCorrCoef
        >>> metric = MulticlassMatthewsCorrCoef(num_classes=3)
        >>> metric.update(jnp.asarray([0, 1, 2, 1]), jnp.asarray([0, 1, 2, 2]))
        >>> round(float(metric.compute()), 4)
        0.7
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, normalize=None, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)

    def _compute(self, state: State):
        return _matthews_corrcoef_reduce(state["confmat"])


class MultilabelMatthewsCorrCoef(MultilabelConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels=num_labels, threshold=threshold, normalize=None,
                         ignore_index=ignore_index, validate_args=validate_args, **kwargs)

    def _compute(self, state: State):
        return _matthews_corrcoef_reduce(state["confmat"])


class MatthewsCorrCoef(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels")}
            return BinaryMatthewsCorrCoef(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            kwargs.pop("num_labels", None)
            return MulticlassMatthewsCorrCoef(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            return MultilabelMatthewsCorrCoef(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
