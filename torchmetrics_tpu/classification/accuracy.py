"""Accuracy metric classes (reference: classification/accuracy.py:31,151,306,461)."""

from __future__ import annotations

from typing import Any

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.core.metric import Metric, State


class BinaryAccuracy(BinaryStatScores):
    """Binary accuracy: fraction of thresholded predictions matching targets (reference classification/accuracy.py:461).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryAccuracy
        >>> metric = BinaryAccuracy()
        >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.5
    """
    _stat_kind = "accuracy"
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _compute(self, state: State):
        return self._reduce_kind(state, "binary")


class MulticlassAccuracy(MulticlassStatScores):
    """Multiclass accuracy over int labels or (N, C) probabilities (reference classification/accuracy.py:151).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import MulticlassAccuracy
        >>> metric = MulticlassAccuracy(num_classes=3, average='micro')
        >>> metric.update(jnp.asarray([0, 1, 2, 1]), jnp.asarray([0, 1, 2, 2]))
        >>> round(float(metric.compute()), 4)
        0.75
    """
    _stat_kind = "accuracy"
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def _compute(self, state: State):
        return self._reduce_kind(state, self.average)


class MultilabelAccuracy(MultilabelStatScores):
    _stat_kind = "accuracy"
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def _compute(self, state: State):
        return self._reduce_kind(state, self.average)


class Accuracy(_ClassificationTaskWrapper):
    """Task dispatch: Accuracy(task="binary"|"multiclass"|"multilabel", ...)."""

    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average", "top_k")}
            return BinaryAccuracy(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            kwargs.pop("num_labels", None)
            return MulticlassAccuracy(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            kwargs.pop("top_k", None)
            return MultilabelAccuracy(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
