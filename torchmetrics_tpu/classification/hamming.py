"""Hamming distance metric classes (reference: classification/hamming.py)."""

from torchmetrics_tpu.classification._factory import make_stat_metric_classes

BinaryHammingDistance, MulticlassHammingDistance, MultilabelHammingDistance, HammingDistance = (
    make_stat_metric_classes(
        "hamming", "BinaryHammingDistance", "MulticlassHammingDistance", "MultilabelHammingDistance",
        "HammingDistance", __name__, higher_is_better=False,
    )
)
