"""Hamming distance metric classes (reference: classification/hamming.py)."""

from torchmetrics_tpu.classification._factory import make_stat_metric_classes

BinaryHammingDistance, MulticlassHammingDistance, MultilabelHammingDistance, HammingDistance = (
    make_stat_metric_classes(
        "hamming", "BinaryHammingDistance", "MulticlassHammingDistance", "MultilabelHammingDistance",
        "HammingDistance", __name__, higher_is_better=False,
    )
)

BinaryHammingDistance.__doc__ = """Binary Hamming distance: fraction of disagreeing labels (reference classification/hamming.py:24).

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.classification import BinaryHammingDistance
    >>> metric = BinaryHammingDistance()
    >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
    >>> round(float(metric.compute()), 4)
    0.5
"""
