"""Average precision metric classes (reference: classification/average_precision.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.average_precision import _ap_from_curve, _binary_ap_compute
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_compute_binned,
)
from torchmetrics_tpu.utilities.compute import _safe_divide


class BinaryAveragePrecision(BinaryPrecisionRecallCurve):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _compute(self, state: State):
        if self.thresholds is None:
            return _binary_ap_compute(*self._exact_state(state), None)
        precision, recall, _ = _binary_precision_recall_curve_compute_binned(state["confmat"], self.thresholds)
        return _ap_from_curve(precision, recall)


class MulticlassAveragePrecision(MulticlassPrecisionRecallCurve):
    """Macro-averaged area under the precision-recall curve (reference classification/average_precision.py:157).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import MulticlassAveragePrecision
        >>> metric = MulticlassAveragePrecision(num_classes=3)
        >>> probs = jnp.asarray([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1], [0.1, 0.2, 0.7], [0.3, 0.4, 0.3]])
        >>> metric.update(probs, jnp.asarray([0, 1, 1, 2]))
        >>> round(float(metric.compute()), 4)
        0.7778
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(self, num_classes: int, average: Optional[str] = "macro", thresholds=None,
                 ignore_index=None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, average=None,
                         ignore_index=ignore_index, validate_args=validate_args, **kwargs)
        self.average_ap = average

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            onehot = jax.nn.one_hot(t, self.num_classes, dtype=jnp.int32)
            aps = jnp.stack([_binary_ap_compute(p[:, c], onehot[:, c], w, None) for c in range(self.num_classes)])
            support = jnp.stack([(onehot[:, c] * w).sum() for c in range(self.num_classes)])
        else:
            confmat = state["confmat"]
            aps, support = [], []
            for c in range(self.num_classes):
                precision, recall, _ = _binary_precision_recall_curve_compute_binned(confmat[:, c], self.thresholds)
                aps.append(_ap_from_curve(precision, recall))
                support.append(confmat[0, c, 1, :].sum())
            aps, support = jnp.stack(aps), jnp.stack(support)
        if self.average_ap in (None, "none"):
            return aps
        if self.average_ap == "macro":
            return jnp.mean(aps)
        if self.average_ap == "weighted":
            return jnp.sum(aps * _safe_divide(support, support.sum()))
        raise ValueError(f"Unknown average {self.average_ap}")


class MultilabelAveragePrecision(MultilabelPrecisionRecallCurve):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(self, num_labels: int, average: Optional[str] = "macro", thresholds=None,
                 ignore_index=None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels=num_labels, thresholds=thresholds,
                         ignore_index=ignore_index, validate_args=validate_args, **kwargs)
        self.average_ap = average

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            if self.average_ap == "micro":
                return _binary_ap_compute(p.reshape(-1), t.reshape(-1), w.reshape(-1), None)
            aps = jnp.stack([_binary_ap_compute(p[:, c], t[:, c], w[:, c], None) for c in range(self.num_labels)])
            support = (t * w).sum(0).astype(jnp.float32)
        else:
            confmat = state["confmat"]
            if self.average_ap == "micro":
                precision, recall, _ = _binary_precision_recall_curve_compute_binned(confmat.sum(1), self.thresholds)
                return _ap_from_curve(precision, recall)
            aps, support = [], []
            for c in range(self.num_labels):
                precision, recall, _ = _binary_precision_recall_curve_compute_binned(confmat[:, c], self.thresholds)
                aps.append(_ap_from_curve(precision, recall))
                support.append(confmat[0, c, 1, :].sum())
            aps, support = jnp.stack(aps), jnp.stack(support)
        if self.average_ap in (None, "none"):
            return aps
        if self.average_ap == "macro":
            return jnp.mean(aps)
        if self.average_ap == "weighted":
            return jnp.sum(aps * _safe_divide(support, support.sum()))
        raise ValueError(f"Unknown average {self.average_ap}")


class AveragePrecision(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average")}
            return BinaryAveragePrecision(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("num_labels", None)
            return MulticlassAveragePrecision(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            return MultilabelAveragePrecision(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
