"""Cohen kappa metric classes (reference: classification/cohen_kappa.py)."""

from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
)
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_reduce


class BinaryCohenKappa(BinaryConfusionMatrix):
    """Cohen's kappa: chance-corrected agreement (reference classification/cohen_kappa.py:26).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryCohenKappa
        >>> metric = BinaryCohenKappa()
        >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.0
    """
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, threshold: float = 0.5, weights: Optional[str] = None,
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold=threshold, normalize=None, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        self.weights = weights

    def _compute(self, state: State):
        return _cohen_kappa_reduce(state["confmat"], self.weights)


class MulticlassCohenKappa(MulticlassConfusionMatrix):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, weights: Optional[str] = None,
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, normalize=None, ignore_index=ignore_index,
                         validate_args=validate_args, **kwargs)
        self.weights = weights

    def _compute(self, state: State):
        return _cohen_kappa_reduce(state["confmat"], self.weights)


class CohenKappa(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs.pop("num_classes", None)
            return BinaryCohenKappa(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            return MulticlassCohenKappa(*args, **kwargs)
        raise ValueError(f"Task {task} not supported! (multilabel not supported for CohenKappa)")
