"""Multilabel ranking metric classes (reference: classification/ranking.py:40,160,280).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import MultilabelRankingAveragePrecision
    >>> metric = MultilabelRankingAveragePrecision(num_labels=3)
    >>> metric.update(jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.6, 0.1]]), jnp.asarray([[1, 0, 1], [0, 0, 1]]))
    >>> round(float(metric.compute()), 4)
    0.6667
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.ranking import (
    multilabel_coverage_error,
    multilabel_ranking_average_precision,
    multilabel_ranking_loss,
)


class _RankingBase(Metric):
    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    _fn = None

    def __init__(self, num_labels: int, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measure", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        n = jnp.asarray(preds).shape[0]
        value = type(self)._fn(preds, target, self.num_labels, self.ignore_index, self.validate_args)
        return {"measure": state["measure"] + value * n, "total": state["total"] + n}

    def _compute(self, state: State) -> Array:
        return state["measure"] / jnp.maximum(state["total"], 1.0)


class MultilabelCoverageError(_RankingBase):
    higher_is_better = False
    _fn = staticmethod(multilabel_coverage_error)


class MultilabelRankingAveragePrecision(_RankingBase):
    higher_is_better = True
    _fn = staticmethod(multilabel_ranking_average_precision)


class MultilabelRankingLoss(_RankingBase):
    higher_is_better = False
    _fn = staticmethod(multilabel_ranking_loss)
