"""Exact match metric classes (reference: classification/exact_match.py)."""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.exact_match import (
    _multiclass_exact_match_stats,
    multilabel_exact_match,
)
from torchmetrics_tpu.functional.classification.stat_scores import _multiclass_validate_args
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _ExactMatchBase(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _init_em_state(self, multidim_average: str) -> None:
        self.multidim_average = multidim_average
        if multidim_average == "samplewise":
            self.add_state("correct", [], dist_reduce_fx="cat")
        else:
            self.add_state("correct", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _accumulate(self, state: State, samplewise: Array, valid_count=None) -> State:
        if self.multidim_average == "samplewise":
            # deliberately an unbounded cat state: the samplewise API returns
            # the per-sample vector itself, so every sample must be kept —
            # there is no sufficient statistic (or sketch) to bound it
            return {"correct": tuple(state["correct"]) + (samplewise,)}
        if valid_count is None:
            valid_count = jnp.asarray(samplewise.shape[0], jnp.float32)
        return {"correct": state["correct"] + jnp.sum(samplewise), "total": state["total"] + valid_count}

    def _compute(self, state: State) -> Array:
        if self.multidim_average == "samplewise":
            return dim_zero_cat(state["correct"])
        return _safe_divide(state["correct"], state["total"])


class MulticlassExactMatch(_ExactMatchBase):
    """MulticlassExactMatch (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import MulticlassExactMatch
        >>> metric = MulticlassExactMatch(num_classes=3)
        >>> metric.update(jnp.asarray([[0, 1], [2, 1]]), jnp.asarray([[0, 1], [2, 2]]))
        >>> round(float(metric.compute()), 4)
        0.5
    """
    def __init__(self, num_classes: int, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_em_state(multidim_average)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        if self.validate_args:
            _multiclass_validate_args(self.num_classes, 1, None, self.multidim_average, self.ignore_index)
        samplewise, sample_valid = _multiclass_exact_match_stats(
            preds, target, self.num_classes, self.ignore_index
        )
        # global total counts samples with >= 1 valid position: under
        # ignore_index a fully-ignored sample must not dilute the mean
        # (matches the functional path's denominator)
        return self._accumulate(state, samplewise, jnp.sum(sample_valid))


class MultilabelExactMatch(_ExactMatchBase):
    def __init__(self, num_labels: int, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_labels = num_labels
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_em_state(multidim_average)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        samplewise = multilabel_exact_match(
            preds, target, self.num_labels, self.threshold, "samplewise", self.ignore_index, self.validate_args
        )
        return self._accumulate(state, samplewise)


class ExactMatch(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            kwargs.pop("num_labels", None)
            return MulticlassExactMatch(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            return MultilabelExactMatch(*args, **kwargs)
        raise ValueError(f"Task {task} not supported! (binary not supported for ExactMatch)")
