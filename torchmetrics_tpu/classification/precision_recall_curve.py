"""Precision-recall curve metric classes (reference: classification/precision_recall_curve.py:55,228,430).

Two state layouts, as in the reference:
* ``thresholds=None`` — exact: cat-list states of (preds, target, weights);
* ``thresholds`` given — binned (T, ..., 2, 2) confusion state, sum-reduced
  (the TPU-friendly layout: static shape, psum-able in-graph).

Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryPrecisionRecallCurve
    >>> metric = BinaryPrecisionRecallCurve(thresholds=None)
    >>> metric.update(jnp.asarray([0.1, 0.6, 0.35, 0.8]), jnp.asarray([0, 1, 0, 1]))
    >>> precision, recall, thresholds = metric.compute()
    >>> precision
    Array([0.5      , 0.6666667, 1.       , 1.       , 1.       ], dtype=float32)
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _adjust_threshold_arg,
    _binary_precision_recall_curve_compute_binned,
    _binary_precision_recall_curve_compute_exact,
    _binary_prc_format,
    _binned_confmat_multiclass,
    _binned_confmat_multilabel,
    _binned_curve_update,
    _multiclass_prc_format,
    _multilabel_prc_format,
    _validate_thresholds,
)
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _CurveBase(Metric):
    """Shared state handling for all curve metrics.

    Three state layouts: the two reference layouts (exact ``cat`` lists for
    ``thresholds=None``, binned ``(T, ..., 2, 2)`` confusion state for given
    thresholds) plus the bounded ``approx="sketch"`` layout — a fixed-grid
    quantile-histogram pair (``torchmetrics_tpu.sketches.QuantileSketch``)
    of shape ``(..., 2, bins + 1)`` that replaces the unbounded cat states.
    In sketch mode the curve is evaluated at the sketch's grid edges, so
    every point lies exactly on the exact curve (the grid only subsamples
    thresholds with spacing ``<= approx_error``) and the cross-device sync
    is one fused ``psum`` instead of a ragged ``all_gather``.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    #: QuantileSketch when ``approx="sketch"`` replaced the cat states
    _sketch = None

    def _init_curve_state(self, thresholds, confmat_shape: Tuple[int, ...]) -> None:
        self.thresholds = _adjust_threshold_arg(thresholds)
        if self.approx == "sketch":
            if self.thresholds is not None:
                raise ValueError(
                    "approx='sketch' replaces the unbounded thresholds=None state; explicit "
                    "`thresholds` are already a bounded binned state — drop one of the two"
                )
            from torchmetrics_tpu.sketches import QuantileSketch

            self._sketch = QuantileSketch.for_error(self.approx_error)
            # curves are computed at the sketch's grid edges, so every
            # binned `_compute` branch below applies to sketch mode unchanged
            self.thresholds = self._sketch.edges
            self.add_state(
                "score_hist",
                self._sketch.init((*confmat_shape, 2)),
                dist_reduce_fx=self._sketch.reduce_spec,
            )
        elif self.thresholds is None:
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("weight", [], dist_reduce_fx="cat")
        else:
            # int32 cell counts (weights are 0/1 ignore-masks, so cells are
            # integral): float32 cells stagnate at 2**24 entries (TMT014).
            # No value_range: fn/tn cells are built by complement subtraction
            # (total - pospred - fn), which interval analysis cannot prove
            # nonnegative, so a (0, inf) declaration would fail TMT017.
            self.add_state(
                "confmat",
                jnp.zeros((self.thresholds.shape[0], *confmat_shape, 2, 2), dtype=jnp.int32),
                dist_reduce_fx="sum",
            )

    @property
    def _binned_update_thresholds(self):
        """Thresholds the per-batch binned confmat update needs — ``None``
        for both unbounded-exact and sketch modes (the sketch accumulates a
        histogram instead; materializing a (T, ..., 2, 2) batch confmat
        would defeat its memory bound)."""
        return None if self._sketch is not None else self.thresholds

    def _sketch_insert(self, hist: Array, p: Array, t: Array, w: Array) -> Array:
        """Fold formatted scores into the (negative, positive) histogram pair."""
        if p.ndim == 2 and t.ndim == 1:  # multiclass scores + integer target
            t = jax.nn.one_hot(t, p.shape[1], dtype=p.dtype)
            w = w[:, None]
        pos = t.astype(p.dtype) * w
        neg = w - pos
        values = jnp.broadcast_to(p[..., None], (*p.shape, 2))
        weights = jnp.stack([neg, pos], axis=-1)
        return self._sketch.insert_batch(hist, values, weights)

    def _accumulate(self, state: State, p: Array, t: Array, w: Array, binned: Array) -> State:
        if self._sketch is not None:
            return {"score_hist": self._sketch_insert(state["score_hist"], p, t, w)}
        if self.thresholds is None:
            return {
                "preds": tuple(state["preds"]) + (p,),
                "target": tuple(state["target"]) + (t,),
                "weight": tuple(state["weight"]) + (w,),
            }
        return {"confmat": state["confmat"] + binned.astype(state["confmat"].dtype)}

    def compute_state(self, state: State):
        if self._sketch is not None:
            # project the histogram pair onto the binned confusion layout —
            # pure and cheap (one reversed cumsum), traced into compute
            state = {**state, "confmat": self._sketch.curve_confmat(state["score_hist"])}
        return super().compute_state(state)


class BinaryPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, ())

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _binary_prc_format(preds, target, self.ignore_index)
        thresholds = self._binned_update_thresholds
        binned = None if thresholds is None else _binned_curve_update(p, t, w, thresholds)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            return _binary_precision_recall_curve_compute_exact(*self._exact_state(state))
        return _binary_precision_recall_curve_compute_binned(state["confmat"], self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(
            (curve[1], curve[0], curve[2]), score=score, ax=ax,
            label_names=("Recall", "Precision"), name=self.__class__.__name__,
        )


class MulticlassPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.num_classes = num_classes
        self.average = average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, (num_classes,))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _multiclass_prc_format(preds, target, self.num_classes, self.ignore_index)
        thresholds = self._binned_update_thresholds
        if thresholds is None:
            binned = None
        else:
            binned = _binned_confmat_multiclass(p, t, w, thresholds, self.num_classes)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            onehot = jax.nn.one_hot(t, self.num_classes, dtype=jnp.int32)
            out = [
                _binary_precision_recall_curve_compute_exact(p[:, c], onehot[:, c], w)
                for c in range(self.num_classes)
            ]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        precision = jnp.concatenate([_safe_divide(tp, tp + fp), jnp.ones((1, self.num_classes))], axis=0).T
        recall = jnp.concatenate([_safe_divide(tp, tp + fn), jnp.zeros((1, self.num_classes))], axis=0).T
        return precision, recall, self.thresholds

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(
            (curve[1], curve[0], curve[2]), score=score, ax=ax,
            label_names=("Recall", "Precision"), name=self.__class__.__name__,
        )


class MultilabelPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        num_labels: int,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, (num_labels,))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _multilabel_prc_format(preds, target, self.num_labels, self.ignore_index)
        thresholds = self._binned_update_thresholds
        if thresholds is None:
            binned = None
        else:
            binned = _binned_confmat_multilabel(p, t, w, thresholds)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            out = [
                _binary_precision_recall_curve_compute_exact(p[:, c], t[:, c], w[:, c])
                for c in range(self.num_labels)
            ]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        precision = jnp.concatenate([_safe_divide(tp, tp + fp), jnp.ones((1, self.num_labels))], axis=0).T
        recall = jnp.concatenate([_safe_divide(tp, tp + fn), jnp.zeros((1, self.num_labels))], axis=0).T
        return precision, recall, self.thresholds


class PrecisionRecallCurve(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average")}
            return BinaryPrecisionRecallCurve(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("num_labels", None)
            return MulticlassPrecisionRecallCurve(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            kwargs.pop("average", None)
            return MultilabelPrecisionRecallCurve(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
