"""Precision-recall curve metric classes (reference: classification/precision_recall_curve.py:55,228,430).

Two state layouts, as in the reference:
* ``thresholds=None`` — exact: cat-list states of (preds, target, weights);
* ``thresholds`` given — binned (T, ..., 2, 2) confusion state, sum-reduced
  (the TPU-friendly layout: static shape, psum-able in-graph).

Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryPrecisionRecallCurve
    >>> metric = BinaryPrecisionRecallCurve(thresholds=None)
    >>> metric.update(jnp.asarray([0.1, 0.6, 0.35, 0.8]), jnp.asarray([0, 1, 0, 1]))
    >>> precision, recall, thresholds = metric.compute()
    >>> precision
    Array([0.5      , 0.6666667, 1.       , 1.       , 1.       ], dtype=float32)
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _adjust_threshold_arg,
    _binary_precision_recall_curve_compute_binned,
    _binary_precision_recall_curve_compute_exact,
    _binary_prc_format,
    _binned_confmat_multiclass,
    _binned_confmat_multilabel,
    _binned_curve_update,
    _multiclass_prc_format,
    _multilabel_prc_format,
    _validate_thresholds,
)
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _CurveBase(Metric):
    """Shared state handling for all curve metrics."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def _init_curve_state(self, thresholds, confmat_shape: Tuple[int, ...]) -> None:
        self.thresholds = _adjust_threshold_arg(thresholds)
        if self.thresholds is None:
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("weight", [], dist_reduce_fx="cat")
        else:
            self.add_state("confmat", jnp.zeros((self.thresholds.shape[0], *confmat_shape, 2, 2)), dist_reduce_fx="sum")

    def _accumulate(self, state: State, p: Array, t: Array, w: Array, binned: Array) -> State:
        if self.thresholds is None:
            return {
                "preds": tuple(state["preds"]) + (p,),
                "target": tuple(state["target"]) + (t,),
                "weight": tuple(state["weight"]) + (w,),
            }
        return {"confmat": state["confmat"] + binned}


class BinaryPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, ())

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _binary_prc_format(preds, target, self.ignore_index)
        binned = None if self.thresholds is None else _binned_curve_update(p, t, w, self.thresholds)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            return _binary_precision_recall_curve_compute_exact(*self._exact_state(state))
        return _binary_precision_recall_curve_compute_binned(state["confmat"], self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(
            (curve[1], curve[0], curve[2]), score=score, ax=ax,
            label_names=("Recall", "Precision"), name=self.__class__.__name__,
        )


class MulticlassPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.num_classes = num_classes
        self.average = average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, (num_classes,))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _multiclass_prc_format(preds, target, self.num_classes, self.ignore_index)
        if self.thresholds is None:
            binned = None
        else:
            binned = _binned_confmat_multiclass(p, t, w, self.thresholds, self.num_classes)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            onehot = jax.nn.one_hot(t, self.num_classes, dtype=jnp.int32)
            out = [
                _binary_precision_recall_curve_compute_exact(p[:, c], onehot[:, c], w)
                for c in range(self.num_classes)
            ]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        precision = jnp.concatenate([_safe_divide(tp, tp + fp), jnp.ones((1, self.num_classes))], axis=0).T
        recall = jnp.concatenate([_safe_divide(tp, tp + fn), jnp.zeros((1, self.num_classes))], axis=0).T
        return precision, recall, self.thresholds

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(
            (curve[1], curve[0], curve[2]), score=score, ax=ax,
            label_names=("Recall", "Precision"), name=self.__class__.__name__,
        )


class MultilabelPrecisionRecallCurve(_CurveBase):
    def __init__(
        self,
        num_labels: int,
        thresholds: Union[int, Sequence[float], Array, None] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _validate_thresholds(thresholds)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_curve_state(thresholds, (num_labels,))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, w = _multilabel_prc_format(preds, target, self.num_labels, self.ignore_index)
        if self.thresholds is None:
            binned = None
        else:
            binned = _binned_confmat_multilabel(p, t, w, self.thresholds)
        return self._accumulate(state, p, t, w, binned)

    def _exact_state(self, state: State) -> Tuple[Array, Array, Array]:
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), dim_zero_cat(state["weight"])

    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            out = [
                _binary_precision_recall_curve_compute_exact(p[:, c], t[:, c], w[:, c])
                for c in range(self.num_labels)
            ]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        precision = jnp.concatenate([_safe_divide(tp, tp + fp), jnp.ones((1, self.num_labels))], axis=0).T
        recall = jnp.concatenate([_safe_divide(tp, tp + fn), jnp.zeros((1, self.num_labels))], axis=0).T
        return precision, recall, self.thresholds


class PrecisionRecallCurve(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average")}
            return BinaryPrecisionRecallCurve(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("num_labels", None)
            return MulticlassPrecisionRecallCurve(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            kwargs.pop("average", None)
            return MultilabelPrecisionRecallCurve(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
