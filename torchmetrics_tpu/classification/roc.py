"""ROC metric classes (reference: classification/roc.py:42,175,346).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import BinaryROC
    >>> metric = BinaryROC(thresholds=None)
    >>> metric.update(jnp.asarray([0.1, 0.6, 0.35, 0.8]), jnp.asarray([0, 1, 0, 1]))
    >>> fpr, tpr, thresholds = metric.compute()
    >>> tpr
    Array([0. , 0.5, 1. , 1. , 1. ], dtype=float32)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute_binned,
    _binary_roc_compute_exact,
)
from torchmetrics_tpu.utilities.compute import _safe_divide


class BinaryROC(BinaryPrecisionRecallCurve):
    def _compute(self, state: State):
        if self.thresholds is None:
            return _binary_roc_compute_exact(*self._exact_state(state))
        return _binary_roc_compute_binned(state["confmat"], self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utilities.plot import plot_curve

        curve = curve if curve is not None else self.compute()
        return plot_curve(
            (curve[0], curve[1], curve[2]), score=score, ax=ax,
            label_names=("False positive rate", "True positive rate"), name=self.__class__.__name__,
        )


class MulticlassROC(MulticlassPrecisionRecallCurve):
    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            onehot = jax.nn.one_hot(t, self.num_classes, dtype=jnp.int32)
            out = [_binary_roc_compute_exact(p[:, c], onehot[:, c], w) for c in range(self.num_classes)]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        tn = confmat[:, :, 0, 0]
        tpr = _safe_divide(tp, tp + fn)[::-1].T
        fpr = _safe_divide(fp, fp + tn)[::-1].T
        return fpr, tpr, self.thresholds[::-1]


class MultilabelROC(MultilabelPrecisionRecallCurve):
    def _compute(self, state: State):
        if self.thresholds is None:
            p, t, w = self._exact_state(state)
            out = [_binary_roc_compute_exact(p[:, c], t[:, c], w[:, c]) for c in range(self.num_labels)]
            return [o[0] for o in out], [o[1] for o in out], [o[2] for o in out]
        confmat = state["confmat"]
        tp = confmat[:, :, 1, 1]
        fp = confmat[:, :, 0, 1]
        fn = confmat[:, :, 1, 0]
        tn = confmat[:, :, 0, 0]
        tpr = _safe_divide(tp, tp + fn)[::-1].T
        fpr = _safe_divide(fp, fp + tn)[::-1].T
        return fpr, tpr, self.thresholds[::-1]


class ROC(_ClassificationTaskWrapper):
    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels")}
            return BinaryROC(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("num_labels", None)
            return MulticlassROC(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            return MultilabelROC(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")
