"""Stateful stat-scores base classes and the StatScores metric family.

Reference: classification/stat_scores.py:43-197 (shared tp/fp/tn/fn states
that the whole Accuracy/Precision/Recall/FBeta tower subclasses).

State layout: ``global`` averaging keeps fixed-shape tp/fp/tn/fn arrays with
``sum`` reduction (psum-able in-graph); ``samplewise`` keeps cat-tuples of
per-sample stats.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.classification._reduce import _stat_reduce
from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_format,
    _binary_stat_scores_update,
    _binary_validate_args,
    _indicator_stat_scores,
    _multiclass_indicators,
    _multiclass_validate_args,
    _multilabel_format,
    _multilabel_stat_scores_update,
    _multilabel_validate_args,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _AbstractStatScores(Metric):
    """Shared state management for the stat-scores tower."""

    _stat_kind: str = "stat_scores"  # overridden by subclasses (accuracy, precision, ...)
    _beta: float = 1.0
    _multilabel: bool = False

    def _create_state(self, size: int, multidim_average: str) -> None:
        if multidim_average == "samplewise":
            for name in ("tp", "fp", "tn", "fn"):
                self.add_state(name, [], dist_reduce_fx="cat")
            return
        # int32, not float32: these are 0/1-indicator sums, and a float32
        # counter silently stops incrementing once it crosses 2**24 (~16.7M
        # samples).  int32 is exact to 2**31 (TMT014 horizon analysis).
        default = jnp.zeros(size, dtype=jnp.int32) if size > 1 else jnp.zeros((), dtype=jnp.int32)
        for name in ("tp", "fp", "tn", "fn"):
            self.add_state(name, default, dist_reduce_fx="sum", value_range=(0.0, float("inf")))

    def _update_stats(self, state: State, tp, fp, tn, fn) -> State:
        if self.multidim_average == "samplewise":
            return {
                "tp": tuple(state["tp"]) + (tp,),
                "fp": tuple(state["fp"]) + (fp,),
                "tn": tuple(state["tn"]) + (tn,),
                "fn": tuple(state["fn"]) + (fn,),
            }
        dtype = state["tp"].dtype
        return {
            "tp": state["tp"] + tp.astype(dtype),
            "fp": state["fp"] + fp.astype(dtype),
            "tn": state["tn"] + tn.astype(dtype),
            "fn": state["fn"] + fn.astype(dtype),
        }

    def _final_state(self, state: State) -> Tuple[Array, Array, Array, Array]:
        if self.multidim_average == "samplewise":
            return (
                dim_zero_cat(state["tp"]),
                dim_zero_cat(state["fp"]),
                dim_zero_cat(state["tn"]),
                dim_zero_cat(state["fn"]),
            )
        return state["tp"], state["fp"], state["tn"], state["fn"]

    def _reduce_kind(self, state: State, average: Optional[str]) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        return _stat_reduce(
            self._stat_kind, tp, fp, tn, fn,
            average=average, multilabel=self._multilabel, beta=self._beta,
            top_k=getattr(self, "top_k", 1), zero_division=getattr(self, "zero_division", 0.0),
        )


class BinaryStatScores(_AbstractStatScores):
    """Binary tp/fp/tn/fn (reference: classification/stat_scores.py:91).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.classification import BinaryStatScores
        >>> metric = BinaryStatScores()
        >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
        >>> metric.compute().tolist()  # [tp, fp, tn, fn, support]
        [1, 1, 1, 1, 2]
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_validate_args(threshold, multidim_average, ignore_index)
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.zero_division = zero_division
        self._create_state(1, multidim_average)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, v = _binary_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _binary_stat_scores_update(p, t, v, self.multidim_average)
        return self._update_stats(state, tp, fp, tn, fn)

    def _compute(self, state: State) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        return jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1).astype(jnp.int32)


class MulticlassStatScores(_AbstractStatScores):
    """Multiclass per-class tp/fp/tn/fn (reference: classification/stat_scores.py:198)."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_validate_args(num_classes, top_k, average, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.top_k = top_k
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.zero_division = zero_division
        self._create_state(num_classes, multidim_average)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        pred_ind, targ_ind, valid = _multiclass_indicators(
            preds, target, self.num_classes, self.top_k, self.ignore_index
        )
        tp, fp, tn, fn = _indicator_stat_scores(pred_ind, targ_ind, valid, self.multidim_average)
        return self._update_stats(state, tp, fp, tn, fn)

    def _compute(self, state: State) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        if self.average == "micro":
            tp, fp, tn, fn = tp.sum(-1), fp.sum(-1), tn.sum(-1), fn.sum(-1)
        return jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1).astype(jnp.int32)


class MultilabelStatScores(_AbstractStatScores):
    """Multilabel per-label tp/fp/tn/fn (reference: classification/stat_scores.py:354)."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False
    _multilabel = True

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        zero_division: float = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_validate_args(num_labels, threshold, average, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.zero_division = zero_division
        self._create_state(num_labels, multidim_average)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        p, t, v = _multilabel_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _multilabel_stat_scores_update(p, t, v, self.multidim_average)
        return self._update_stats(state, tp, fp, tn, fn)

    def _compute(self, state: State) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        if self.average == "micro":
            tp, fp, tn, fn = tp.sum(-1), fp.sum(-1), tn.sum(-1), fn.sum(-1)
        return jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1).astype(jnp.int32)


class StatScores(_ClassificationTaskWrapper):
    """Task-dispatch wrapper (reference: classification/stat_scores.py:471)."""

    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        kwargs.pop("task", None)
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average", "top_k")}
            return BinaryStatScores(**kwargs)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            kwargs.pop("num_labels", None)
            return MulticlassStatScores(**kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            kwargs.pop("top_k", None)
            return MultilabelStatScores(**kwargs)
        raise ValueError(f"Task {task} not supported!")
