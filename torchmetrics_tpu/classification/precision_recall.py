"""Precision / Recall metric classes (reference: classification/precision_recall.py:40-796)."""

from torchmetrics_tpu.classification._factory import make_stat_metric_classes

BinaryPrecision, MulticlassPrecision, MultilabelPrecision, Precision = make_stat_metric_classes(
    "precision", "BinaryPrecision", "MulticlassPrecision", "MultilabelPrecision", "Precision", __name__
)

BinaryRecall, MulticlassRecall, MultilabelRecall, Recall = make_stat_metric_classes(
    "recall", "BinaryRecall", "MulticlassRecall", "MultilabelRecall", "Recall", __name__
)
