"""Precision / Recall metric classes (reference: classification/precision_recall.py:40-796)."""

from torchmetrics_tpu.classification._factory import make_stat_metric_classes

BinaryPrecision, MulticlassPrecision, MultilabelPrecision, Precision = make_stat_metric_classes(
    "precision", "BinaryPrecision", "MulticlassPrecision", "MultilabelPrecision", "Precision", __name__
)

BinaryRecall, MulticlassRecall, MultilabelRecall, Recall = make_stat_metric_classes(
    "recall", "BinaryRecall", "MulticlassRecall", "MultilabelRecall", "Recall", __name__
)

BinaryPrecision.__doc__ = """Binary precision: TP / (TP + FP) (reference classification/precision_recall.py:28).

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.classification import BinaryPrecision
    >>> metric = BinaryPrecision()
    >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
    >>> round(float(metric.compute()), 4)
    0.5
"""

BinaryRecall.__doc__ = """Binary recall: TP / (TP + FN) (reference classification/precision_recall.py:450).

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.classification import BinaryRecall
    >>> metric = BinaryRecall()
    >>> metric.update(jnp.asarray([0.2, 0.8, 0.6, 0.3]), jnp.asarray([0, 1, 0, 1]))
    >>> round(float(metric.compute()), 4)
    0.5
"""
