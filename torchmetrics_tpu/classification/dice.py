"""Dice metric class (reference: classification/dice.py:31).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import Dice
    >>> metric = Dice(average='micro', num_classes=3)
    >>> metric.update(jnp.asarray([2, 0, 2, 1]), jnp.asarray([1, 0, 2, 1]))
    >>> round(float(metric.compute()), 4)
    0.75
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.classification.stat_scores import MulticlassStatScores
from torchmetrics_tpu.core.metric import State
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide
from torchmetrics_tpu.utilities.formatting import classify_inputs


class Dice(MulticlassStatScores):
    """Dice score: 2*tp / (2*tp + fp + fn) over flexible-format inputs.

    This is the legacy-style entry point: like the reference
    (classification/dice.py:31 via ``_input_format_classification``,
    utilities/checks.py:315), it accepts binary probabilities ``(N,)`` (with
    ``multiclass=True``, as the reference requires), ``(N, C)``
    probabilities/logits, integer labels, multilabel masks, and
    multi-dim variants — all canonicalized through
    :func:`~torchmetrics_tpu.utilities.formatting.classify_inputs` before the
    per-class stat-score accumulation.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, average: Optional[str] = "micro",
                 ignore_index: Optional[int] = None, top_k: int = 1,
                 threshold: float = 0.5, multiclass: Optional[bool] = None,
                 **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, top_k=top_k, average=average,
                         ignore_index=ignore_index, **kwargs)
        self.threshold = threshold
        self.multiclass = multiclass

    def _update(self, state: State, preds: Array, target: Array) -> State:
        # binary inputs with num_classes=2 require an explicit
        # multiclass=True, exactly like the reference (checks.py raises the
        # same "Set it to True if you want to transform binary data" error)
        p, t, case = classify_inputs(
            preds, target, threshold=self.threshold,
            top_k=None if self.top_k == 1 else self.top_k,
            num_classes=self.num_classes, multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        if p.shape[1] != self.num_classes:
            raise ValueError(
                f"Inputs canonicalized to {p.shape[1]} classes but `num_classes={self.num_classes}` "
                f"(detected case: {case.value})"
            )
        # fold multi-dim positions into the sample axis: (N, C[, X]) -> (N*X, C)
        if p.ndim == 3:
            p = jnp.moveaxis(p, 1, 2).reshape(-1, p.shape[1])
            t = jnp.moveaxis(t, 1, 2).reshape(-1, t.shape[1])
        tp = ((p == 1) & (t == 1)).sum(axis=0)
        fp = ((p == 1) & (t == 0)).sum(axis=0)
        tn = ((p == 0) & (t == 0)).sum(axis=0)
        fn = ((p == 0) & (t == 1)).sum(axis=0)
        return self._update_stats(state, tp, fp, tn, fn)

    def _compute(self, state: State) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        if self.ignore_index is not None:
            # ignore_index removes the CLASS from every reduction — samples
            # keep contributing to the other classes (reference
            # _reduce_stat_scores drops the index, dice.py via stat_scores)
            keep = np.arange(self.num_classes) != self.ignore_index
            tp, fp, tn, fn = tp[..., keep], fp[..., keep], tn[..., keep], fn[..., keep]
        if self.average == "micro":
            tp, fp, fn = tp.sum(), fp.sum(), fn.sum()
            return _safe_divide(2 * tp, 2 * tp + fp + fn)
        score = _safe_divide(2 * tp, 2 * tp + fp + fn)
        return _adjust_weights_safe_divide(score, self.average, False, tp, fp, fn)
