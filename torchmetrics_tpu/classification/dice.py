"""Dice metric class (reference: classification/dice.py:31).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.classification import Dice
    >>> metric = Dice(average='micro', num_classes=3)
    >>> metric.update(jnp.asarray([2, 0, 2, 1]), jnp.asarray([1, 0, 2, 1]))
    >>> round(float(metric.compute()), 4)
    0.75
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.stat_scores import MulticlassStatScores
from torchmetrics_tpu.core.metric import State
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide


class Dice(MulticlassStatScores):
    """Dice score: 2*tp / (2*tp + fp + fn) over multiclass stat scores."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, average: Optional[str] = "micro",
                 ignore_index: Optional[int] = None, top_k: int = 1, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, top_k=top_k, average=average,
                         ignore_index=ignore_index, **kwargs)

    def _compute(self, state: State) -> Array:
        tp, fp, tn, fn = self._final_state(state)
        if self.average == "micro":
            tp, fp, fn = tp.sum(), fp.sum(), fn.sum()
            return _safe_divide(2 * tp, 2 * tp + fp + fn)
        score = _safe_divide(2 * tp, 2 * tp + fp + fn)
        return _adjust_weights_safe_divide(score, self.average, False, tp, fp, fn)
