"""Task-string dispatch base (reference: classification/base.py:19).

``Accuracy(task="multiclass", num_classes=5)`` returns a
``MulticlassAccuracy`` instance via ``__new__`` — the same ergonomic the
reference's ``_ClassificationTaskWrapper`` provides.
"""

from __future__ import annotations

from typing import Any

from torchmetrics_tpu.core.metric import Metric


class _ClassificationTaskWrapper(Metric):
    """Base for wrapper classes that dispatch to task-specific metrics in ``__new__``."""

    def __new__(cls, task: Any = None, *args: Any, **kwargs: Any) -> "Metric":
        task = kwargs.pop("task", task)
        return cls._create_task_metric(task, *args, **kwargs)

    @classmethod
    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        raise NotImplementedError

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError(f"{self.__class__.__name__} metric does not exist for the chosen task")

    def compute(self) -> None:
        raise NotImplementedError(f"{self.__class__.__name__} metric does not exist for the chosen task")
