"""Class factory for the stat-scores-derived metric tower.

The reference hand-writes ~27 near-identical classes
(classification/{precision_recall,specificity,hamming,...}.py); here each
(kind, task) class is generated once with proper names so
pickling/introspection behave like hand-written classes.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Type

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.core.metric import Metric, State


def make_stat_metric_classes(
    kind: str,
    binary_name: str,
    multiclass_name: str,
    multilabel_name: str,
    wrapper_name: str,
    module: str,
    higher_is_better: bool = True,
) -> Tuple[type, type, type, type]:
    """Build (Binary*, Multiclass*, Multilabel*, task-wrapper) classes for a stat kind."""

    def _binary_compute(self, state: State):
        return self._reduce_kind(state, "binary")

    def _avg_compute(self, state: State):
        return self._reduce_kind(state, self.average)

    common = {
        "_stat_kind": kind,
        "is_differentiable": False,
        "higher_is_better": higher_is_better,
        "full_state_update": False,
        "plot_lower_bound": 0.0,
        "plot_upper_bound": 1.0,
        "__module__": module,
    }
    binary_cls = type(binary_name, (BinaryStatScores,), {**common, "_compute": _binary_compute})
    multiclass_cls = type(
        multiclass_name, (MulticlassStatScores,), {**common, "plot_legend_name": "Class", "_compute": _avg_compute}
    )
    multilabel_cls = type(
        multilabel_name, (MultilabelStatScores,), {**common, "plot_legend_name": "Label", "_compute": _avg_compute}
    )

    def _create_task_metric(cls, task: str, *args: Any, **kwargs: Any) -> Metric:
        task = str(task)
        if task == "binary":
            kwargs = {k: v for k, v in kwargs.items() if k not in ("num_classes", "num_labels", "average", "top_k")}
            return binary_cls(*args, **kwargs)
        if task == "multiclass":
            kwargs.pop("threshold", None)
            kwargs.pop("num_labels", None)
            return multiclass_cls(*args, **kwargs)
        if task == "multilabel":
            kwargs.pop("num_classes", None)
            kwargs.pop("top_k", None)
            return multilabel_cls(*args, **kwargs)
        raise ValueError(f"Task {task} not supported!")

    wrapper_cls = type(
        wrapper_name,
        (_ClassificationTaskWrapper,),
        {"__module__": module, "_create_task_metric": classmethod(_create_task_metric)},
    )
    return binary_cls, multiclass_cls, multilabel_cls, wrapper_cls
