"""Elementwise-error regression metric classes.

Reference: regression/{mse,mae,mape,symmetric_mape,weighted_mape,msle,
log_cosh,minkowski,tweedie_deviance,csi}.py (e.g. mse.py:28).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.regression.basic import (
    _critical_success_index_update,
    _log_cosh_error_update,
    _mean_absolute_error_update,
    _mean_absolute_percentage_error_update,
    _mean_squared_error_update,
    _mean_squared_log_error_update,
    _minkowski_distance_update,
    _symmetric_mape_update,
    _tweedie_deviance_update,
    _weighted_mape_update,
    _EPS,
)
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError


class _SumCountMetric(Metric):
    """Base for (Σerror, n) metrics."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    #: dtype of the ``total`` counter.  Element counts are integers, and a
    #: float32 count silently stops incrementing at 2**24 (~16.7M samples;
    #: TMT014 horizon analysis) — subclasses whose ``total`` is a fractional
    #: weight sum (WeightedMAPE) override this back to float32.
    _count_dtype = jnp.int32

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        default = jnp.zeros(num_outputs) if num_outputs > 1 else jnp.zeros(())
        self.add_state("measure", default, dist_reduce_fx="sum", value_range=(0.0, float("inf")))
        self.add_state(
            "total", jnp.zeros((), dtype=self._count_dtype), dist_reduce_fx="sum", value_range=(0.0, float("inf"))
        )

    def _compute(self, state: State) -> Array:
        return state["measure"] / jnp.maximum(state["total"], 1.0)


class MeanSquaredError(_SumCountMetric):
    """Mean squared error (reference regression/mse.py:27).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanSquaredError
        >>> metric = MeanSquaredError()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.375
    """
    def __init__(self, squared: bool = True, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(num_outputs=num_outputs, **kwargs)
        if not isinstance(squared, bool):
            raise ValueError(f"Expected argument `squared` to be a boolean but got {squared}")
        self.squared = squared

    def _update(self, state: State, preds: Array, target: Array) -> State:
        sse, n = _mean_squared_error_update(preds, target, self.num_outputs)
        return {"measure": state["measure"] + sse, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}

    def _compute(self, state: State) -> Array:
        mse = super()._compute(state)
        return mse if self.squared else jnp.sqrt(mse)


class MeanAbsoluteError(_SumCountMetric):
    """Mean absolute error (reference regression/mae.py:26).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanAbsoluteError
        >>> metric = MeanAbsoluteError()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.5
    """
    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(num_outputs=num_outputs, **kwargs)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        sae, n = _mean_absolute_error_update(preds, target, self.num_outputs)
        return {"measure": state["measure"] + sae, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class MeanAbsolutePercentageError(_SumCountMetric):
    """MeanAbsolutePercentageError (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MeanAbsolutePercentageError
        >>> metric = MeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.3274
    """
    def _update(self, state: State, preds: Array, target: Array) -> State:
        s, n = _mean_absolute_percentage_error_update(preds, target)
        return {"measure": state["measure"] + s, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class SymmetricMeanAbsolutePercentageError(_SumCountMetric):
    """SymmetricMeanAbsolutePercentageError (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import SymmetricMeanAbsolutePercentageError
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.5788
    """
    def _update(self, state: State, preds: Array, target: Array) -> State:
        s, n = _symmetric_mape_update(preds, target)
        return {"measure": state["measure"] + s, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class WeightedMeanAbsolutePercentageError(_SumCountMetric):
    _count_dtype = jnp.float32  # total is a fractional sum of |target|, not an element count

    def _update(self, state: State, preds: Array, target: Array) -> State:
        num, denom = _weighted_mape_update(preds, target)
        return {"measure": state["measure"] + num, "total": state["total"] + denom}

    def _compute(self, state: State) -> Array:
        return state["measure"] / jnp.maximum(state["total"], _EPS)


class MeanSquaredLogError(_SumCountMetric):
    def _update(self, state: State, preds: Array, target: Array) -> State:
        s, n = _mean_squared_log_error_update(preds, target)
        return {"measure": state["measure"] + s, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class LogCoshError(_SumCountMetric):
    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(num_outputs=num_outputs, **kwargs)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        s, n = _log_cosh_error_update(preds, target, self.num_outputs)
        return {"measure": state["measure"] + s, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class MinkowskiDistance(Metric):
    """MinkowskiDistance (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import MinkowskiDistance
        >>> metric = MinkowskiDistance(p=3.0)
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        1.0772
    """
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, p: float, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(p, (int, float)) and p >= 1):
            raise TorchMetricsUserError(f"Argument ``p`` should be a float or int greater than 1, but got {p}")
        self.p = p
        self.add_state("minkowski_dist_sum", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        return {"minkowski_dist_sum": state["minkowski_dist_sum"] + _minkowski_distance_update(preds, target, self.p)}

    def _compute(self, state: State) -> Array:
        return state["minkowski_dist_sum"] ** (1.0 / self.p)


class TweedieDevianceScore(_SumCountMetric):
    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power

    def _update(self, state: State, preds: Array, target: Array) -> State:
        s, n = _tweedie_deviance_update(preds, target, self.power)
        return {"measure": state["measure"] + s, "total": state["total"] + jnp.asarray(n, state["total"].dtype)}


class CriticalSuccessIndex(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, threshold: float, keep_sequence_dim: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.threshold = threshold
        self.keep_sequence_dim = keep_sequence_dim
        if keep_sequence_dim is None:
            self.add_state("hits", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("misses", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("false_alarms", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("hits", [], dist_reduce_fx="cat")
            self.add_state("misses", [], dist_reduce_fx="cat")
            self.add_state("false_alarms", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        hits, misses, fa = _critical_success_index_update(preds, target, self.threshold, self.keep_sequence_dim)
        if self.keep_sequence_dim is None:
            return {
                "hits": state["hits"] + hits,
                "misses": state["misses"] + misses,
                "false_alarms": state["false_alarms"] + fa,
            }
        return {
            "hits": tuple(state["hits"]) + (hits,),
            "misses": tuple(state["misses"]) + (misses,),
            "false_alarms": tuple(state["false_alarms"]) + (fa,),
        }

    def _compute(self, state: State) -> Array:
        from torchmetrics_tpu.utilities.data import dim_zero_cat

        if self.keep_sequence_dim is None:
            hits, misses, fa = state["hits"], state["misses"], state["false_alarms"]
        else:
            hits = dim_zero_cat(state["hits"])
            misses = dim_zero_cat(state["misses"])
            fa = dim_zero_cat(state["false_alarms"])
        return _safe_divide(hits, hits + misses + fa)
