"""KL divergence and cosine similarity metric classes (reference: regression/{kl_divergence,cosine_similarity}.py)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.regression.basic import (
    _cosine_similarity_compute,
    _kl_divergence_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class KLDivergence(Metric):
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, log_prob: bool = False, reduction: str = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(log_prob, bool):
            raise TypeError(f"Expected argument `log_prob` to be bool but got {log_prob}")
        allowed = ("mean", "sum", "none", None)
        if reduction not in allowed:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed} but got {reduction}")
        self.log_prob = log_prob
        self.reduction = reduction
        if reduction in ("mean", "sum"):
            self.add_state("measures", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("measures", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, p: Array, q: Array) -> State:
        measures, n = _kl_divergence_update(p, q, self.log_prob)
        if self.reduction in ("mean", "sum"):
            return {"measures": state["measures"] + jnp.sum(measures), "total": state["total"] + n}
        return {"measures": tuple(state["measures"]) + (measures,), "total": state["total"] + n}

    def _compute(self, state: State) -> Array:
        if self.reduction == "mean":
            return state["measures"] / jnp.maximum(state["total"], 1.0)
        if self.reduction == "sum":
            return state["measures"]
        return dim_zero_cat(state["measures"])


class CosineSimilarity(Metric):
    """CosineSimilarity (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import CosineSimilarity
        >>> metric = CosineSimilarity(reduction='mean')
        >>> metric.update(jnp.asarray([[1.0, 2.0, 3.0]]), jnp.asarray([[1.0, 2.0, 4.0]]))
        >>> round(float(metric.compute()), 4)
        0.9915
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, reduction: str = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed = ("sum", "mean", "none", None)
        if reduction not in allowed:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        return {
            "preds": tuple(state["preds"]) + (jnp.asarray(preds, jnp.float32),),
            "target": tuple(state["target"]) + (jnp.asarray(target, jnp.float32),),
        }

    def _compute(self, state: State) -> Array:
        return _cosine_similarity_compute(
            dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), self.reduction
        )
