"""Correlation metric classes (reference: regression/{pearson,spearman,kendall,concordance}.py).

PearsonCorrCoef keeps Welford-mergeable moment states and overrides
``merge_states``/``sync_states`` with the parallel combine — the reference
equivalently gathers per-rank moments and runs ``_final_aggregation``
(reference pearson.py:73).  Spearman/Kendall cat-gather raw data (rank
statistics are not sum-decomposable), as in the reference.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State, _N
from torchmetrics_tpu.core.reductions import Reduce, sync_leaf
from torchmetrics_tpu.functional.regression.correlation import (
    _final_aggregation,
    _pearson_compute,
    _pearson_update,
    _rank_data_average,
    kendall_rank_corrcoef,
    pearson_corrcoef,
    spearman_corrcoef,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class PearsonCorrCoef(Metric):
    """Streaming Pearson correlation from mergeable moment states.

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import PearsonCorrCoef
        >>> metric = PearsonCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.9849
    """
    is_differentiable = True
    higher_is_better = None
    full_state_update = True
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_outputs, int) and num_outputs > 0):
            raise ValueError(f"Expected argument `num_outputs` to be an int larger than 0, but got {num_outputs}")
        self.num_outputs = num_outputs
        d = jnp.zeros(num_outputs)
        for name in ("mean_x", "mean_y", "var_x", "var_y", "corr_xy"):
            self.add_state(name, d, dist_reduce_fx=None)
        self.add_state("n_total", jnp.zeros(()), dist_reduce_fx=None)

    def _update(self, state: State, preds: Array, target: Array) -> State:
        mx, my, vx, vy, cxy, n = _pearson_update(
            preds, target, state["mean_x"], state["mean_y"], state["var_x"],
            state["var_y"], state["corr_xy"], state["n_total"],
        )
        return {"mean_x": mx, "mean_y": my, "var_x": vx, "var_y": vy, "corr_xy": cxy, "n_total": n}

    def merge_states(self, a: State, b: State) -> State:
        mx, my, vx, vy, cxy, n = _final_aggregation(
            jnp.stack([a["mean_x"], b["mean_x"]]),
            jnp.stack([a["mean_y"], b["mean_y"]]),
            jnp.stack([a["var_x"], b["var_x"]]),
            jnp.stack([a["var_y"], b["var_y"]]),
            jnp.stack([a["corr_xy"], b["corr_xy"]]),
            jnp.stack([a["n_total"], b["n_total"]]),
        )
        return {
            "mean_x": mx, "mean_y": my, "var_x": vx, "var_y": vy,
            "corr_xy": cxy, "n_total": n, _N: a[_N] + b[_N],
        }

    def sync_states(self, state: State, axis_name: Optional[str] = None) -> State:
        # moment states are not leaf-wise combinable, so this bypasses the
        # coalescing planner: stack every device's moments (Reduce.NONE
        # lowers to the same all_gather the planner's passthrough uses) and
        # run the pairwise aggregation on the stacked copies
        axis_name = axis_name or self.axis_name
        gathered = {
            k: sync_leaf(Reduce.NONE, v, axis_name) for k, v in state.items() if k != _N
        }
        mx, my, vx, vy, cxy, n = _final_aggregation(
            gathered["mean_x"], gathered["mean_y"], gathered["var_x"],
            gathered["var_y"], gathered["corr_xy"], gathered["n_total"],
        )
        return {
            "mean_x": mx, "mean_y": my, "var_x": vx, "var_y": vy,
            "corr_xy": cxy, "n_total": n, _N: sync_leaf(Reduce.SUM, state[_N], axis_name),
        }

    def host_sync_states(self, state: State) -> State:
        """DCN mirror of the in-graph override: gather each process's moment
        state, then run the same pairwise aggregation."""
        from jax.experimental import multihost_utils

        gathered = {
            k: jnp.asarray(multihost_utils.process_allgather(v))
            for k, v in state.items()
            if k != _N
        }
        mx, my, vx, vy, cxy, n = _final_aggregation(
            gathered["mean_x"], gathered["mean_y"], gathered["var_x"],
            gathered["var_y"], gathered["corr_xy"], gathered["n_total"],
        )
        n_updates = jnp.sum(jnp.asarray(multihost_utils.process_allgather(state[_N])))
        return {
            "mean_x": mx, "mean_y": my, "var_x": vx, "var_y": vy,
            "corr_xy": cxy, "n_total": n, _N: n_updates,
        }

    def _compute(self, state: State) -> Array:
        return _pearson_compute(state["var_x"], state["var_y"], state["corr_xy"], state["n_total"])


class ConcordanceCorrCoef(PearsonCorrCoef):
    """Lin's CCC from the same moment states (reference: regression/concordance.py)."""

    def _compute(self, state: State) -> Array:
        # n-1 normalization matches the reference exactly
        # (functional/regression/pearson.py:95-97 feeding concordance.py:30)
        n = jnp.maximum(state["n_total"] - 1.0, 1.0)
        vx = state["var_x"] / n
        vy = state["var_y"] / n
        cxy = state["corr_xy"] / n
        ccc = 2 * cxy / (vx + vy + (state["mean_x"] - state["mean_y"]) ** 2)
        return ccc.squeeze()


class _CatCorrBase(Metric):
    """Base for metrics requiring the full data (rank statistics)."""

    is_differentiable = False
    full_state_update = False

    def __init__(self, num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        return {
            "preds": tuple(state["preds"]) + (jnp.asarray(preds, jnp.float32),),
            "target": tuple(state["target"]) + (jnp.asarray(target, jnp.float32),),
        }


class SpearmanCorrCoef(_CatCorrBase):
    """Spearman rank correlation over the full accumulated sample (reference regression/spearman.py:28).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import SpearmanCorrCoef
        >>> metric = SpearmanCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        1.0
    """
    higher_is_better = None
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def _compute(self, state: State) -> Array:
        return spearman_corrcoef(dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]))


class KendallRankCorrCoef(_CatCorrBase):
    """KendallRankCorrCoef (see module docstring for the reference mapping).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import KendallRankCorrCoef
        >>> metric = KendallRankCorrCoef()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        1.0
    """
    higher_is_better = None
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, variant: str = "b", t_test: bool = False,
                 alternative: str = "two-sided", num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(num_outputs=num_outputs, **kwargs)
        self.variant = variant
        self.t_test = t_test
        self.alternative = alternative

    def _compute(self, state: State) -> Array:
        return kendall_rank_corrcoef(
            dim_zero_cat(state["preds"]), dim_zero_cat(state["target"]), self.variant
        )
