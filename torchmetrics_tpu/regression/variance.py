"""R² / explained variance / RSE metric classes (reference: regression/{r2,explained_variance,rse}.py)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.regression.variance import (
    _explained_variance_compute,
    _explained_variance_update,
    _r2_score_compute,
    _r2_score_update,
)


class R2Score(Metric):
    """Coefficient of determination (reference regression/r2.py:32).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import R2Score
        >>> metric = R2Score()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.9486
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_upper_bound = 1.0

    def __init__(self, num_outputs: int = 1, adjusted: int = 0,
                 multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted
        allowed = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed}")
        self.multioutput = multioutput
        d = jnp.zeros(num_outputs)
        self.add_state("sum_squared_error", d, dist_reduce_fx="sum")
        self.add_state("sum_error", d, dist_reduce_fx="sum")
        self.add_state("sum_squared_target", d, dist_reduce_fx="sum")
        # int32: sample counts are integers and a float32 count stagnates at
        # 2**24 (~16.7M samples; TMT014 horizon analysis)
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        residual, sum_target, sum_sq_target, n = _r2_score_update(preds, target)
        return {
            "sum_squared_error": state["sum_squared_error"] + residual,
            "sum_error": state["sum_error"] + sum_target,
            "sum_squared_target": state["sum_squared_target"] + sum_sq_target,
            "total": state["total"] + jnp.asarray(n, state["total"].dtype),
        }

    def _compute(self, state: State) -> Array:
        return _r2_score_compute(
            state["sum_squared_error"], state["sum_error"], state["sum_squared_target"],
            state["total"], self.adjusted, self.multioutput,
        )


class ExplainedVariance(Metric):
    """Explained variance ratio (reference regression/explained_variance.py:30).

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.regression import ExplainedVariance
        >>> metric = ExplainedVariance()
        >>> metric.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> round(float(metric.compute()), 4)
        0.9572
    """
    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_upper_bound = 1.0

    def __init__(self, multioutput: str = "uniform_average", num_outputs: int = 1, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed:
            raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {allowed}")
        self.multioutput = multioutput
        d = jnp.zeros(num_outputs)
        self.add_state("num_obs", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))
        self.add_state("sum_error", d, dist_reduce_fx="sum")
        self.add_state("sum_squared_error", d, dist_reduce_fx="sum")
        self.add_state("sum_target", d, dist_reduce_fx="sum")
        self.add_state("sum_squared_target", d, dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        n, se, sse, st, sst = _explained_variance_update(preds, target)
        return {
            "num_obs": state["num_obs"] + jnp.asarray(n, state["num_obs"].dtype),
            "sum_error": state["sum_error"] + se,
            "sum_squared_error": state["sum_squared_error"] + sse,
            "sum_target": state["sum_target"] + st,
            "sum_squared_target": state["sum_squared_target"] + sst,
        }

    def _compute(self, state: State) -> Array:
        return _explained_variance_compute(
            state["num_obs"], state["sum_error"], state["sum_squared_error"],
            state["sum_target"], state["sum_squared_target"], self.multioutput,
        )


class RelativeSquaredError(Metric):
    is_differentiable = True
    higher_is_better = False
    full_state_update = False

    def __init__(self, num_outputs: int = 1, squared: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs
        self.squared = squared
        d = jnp.zeros(num_outputs)
        self.add_state("sum_squared_error", d, dist_reduce_fx="sum")
        self.add_state("sum_error", d, dist_reduce_fx="sum")
        self.add_state("sum_squared_target", d, dist_reduce_fx="sum")
        # int32: sample counts are integers and a float32 count stagnates at
        # 2**24 (~16.7M samples; TMT014 horizon analysis)
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        residual, sum_target, sum_sq_target, n = _r2_score_update(preds, target)
        return {
            "sum_squared_error": state["sum_squared_error"] + residual,
            "sum_error": state["sum_error"] + sum_target,
            "sum_squared_target": state["sum_squared_target"] + sum_sq_target,
            "total": state["total"] + jnp.asarray(n, state["total"].dtype),
        }

    def _compute(self, state: State) -> Array:
        mean_target = state["sum_error"] / state["total"]
        ss_tot = state["sum_squared_target"] - state["sum_error"] * mean_target
        rse = jnp.sum(state["sum_squared_error"]) / jnp.maximum(jnp.sum(ss_tot), 1e-24)
        return rse if self.squared else jnp.sqrt(rse)
