"""InfoLM modular metric (reference: text/infolm.py:41-220).
Example::

    >>> from torchmetrics_tpu.text import InfoLM
    >>> metric = InfoLM(information_measure='l2_distance', idf=False, verbose=False)
    >>> metric.update(['the cat sat on the mat'], ['the cat sat on the mat'])
    >>> round(float(metric.compute()), 4)  # identical pair -> zero distance
    0.0
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.infolm import infolm
from torchmetrics_tpu.utilities.data import dim_zero_cat


class InfoLM(Metric):
    """InfoLM; per-sentence scores kept as cat state (reference text/infolm.py
    stores tokenized inputs; scores are equivalent and smaller)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        model_name_or_path: str = "bert-base-uncased",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Optional[Any] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        # validate measure/alpha/beta now, not on the first update
        from torchmetrics_tpu.functional.text.infolm import _InformationMeasure

        _InformationMeasure(information_measure, alpha, beta)
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.max_length = max_length
        self.return_sentence_level_score = return_sentence_level_score
        self.model = model
        self.user_tokenizer = user_tokenizer

        self.add_state("scores", [], dist_reduce_fx="cat")

    def _update(
        self, state: State, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
    ) -> State:
        _, per_sentence = infolm(
            preds, target,
            model_name_or_path=self.model_name_or_path,
            temperature=self.temperature,
            information_measure=self.information_measure,
            idf=self.idf,
            alpha=self.alpha,
            beta=self.beta,
            max_length=self.max_length,
            return_sentence_level_score=True,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
        )
        return {"scores": state["scores"] + (per_sentence,)}

    def _compute(self, state: State) -> Union[Array, Tuple[Array, Array]]:
        if not state["scores"]:
            return jnp.zeros(())
        scores = dim_zero_cat(state["scores"])
        if self.return_sentence_level_score:
            return scores.mean(), scores
        return scores.mean()
