"""ROUGE modular metric (reference: text/rouge.py:36-220).

``approx="reservoir"`` replaces the per-sample cat states (three floats per
sample per rouge key, gathered raggedly at sync) with a deterministic
bottom-k-by-hash corpus sample (:class:`~torchmetrics_tpu.sketches.ReservoirSketch`):
a fixed ``(sample_size, 1 + 3·len(rouge_keys))`` reservoir keyed by a content
hash of each prediction, synced as ONE fixed-shape gather regardless of
corpus size, plus an exact SUM counter of samples seen.  The estimator is the
mean over kept rows; since every per-sample stat lies in [0, 1], the mean
over the full corpus deviates from the kept-sample mean by at most
``(n - k)/n · max(m̄, 1 - m̄)`` — zero while the corpus fits the reservoir —
which is the data-dependent bound stamped into the attestation plane.
"""

from __future__ import annotations

import zlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_update,
)
from torchmetrics_tpu.sketches.reservoir import ReservoirSketch
from torchmetrics_tpu.utilities.data import dim_zero_cat

_STATS = ("fmeasure", "precision", "recall")


def content_key(text: str, salt: int = 0) -> int:
    """Deterministic integer key of a sample's content — the reservoir
    priority seed (same sample → same priority on every replica/trace)."""
    return (zlib.crc32(text.encode("utf-8")) ^ (salt * 0x9E3779B1)) & 0xFFFFFFFF


class ROUGEScore(Metric):
    """ROUGE-N/L/Lsum; per-sample P/R/F stored as cat states so the sync path
    moves only tensors (reference text/rouge.py:143 stores the same).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import ROUGEScore
        >>> metric = ROUGEScore(rouge_keys='rouge1')
        >>> metric.update("the cat is on the mat", "a cat is on the mat")
        >>> round(float(metric.compute()['rouge1_fmeasure']), 4)
        0.8333
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        sample_size: int = 1024,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer:
            try:
                from nltk.stem.porter import PorterStemmer  # type: ignore  # noqa: F401
            except ImportError as err:
                raise ModuleNotFoundError("Stemmer requires the `nltk` package which is not installed.") from err
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[k] for k in rouge_keys]
        self.use_stemmer = use_stemmer
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        if use_stemmer:
            from nltk.stem.porter import PorterStemmer  # type: ignore

            self.stemmer = PorterStemmer()
        else:
            self.stemmer = None

        if not (isinstance(sample_size, int) and sample_size >= 1):
            raise ValueError(f"Argument `sample_size` must be a positive int, got {sample_size!r}")
        #: reservoir capacity under ``approx="reservoir"`` (rows kept)
        self.sample_size = sample_size
        self._install_approx_states()

    def _install_approx_states(self) -> None:
        """(Re-)register state leaves for the current ``approx`` config —
        the :meth:`~torchmetrics_tpu.core.metric.Metric.set_approx` hook."""
        if self.approx == "reservoir":
            self._reservoir = ReservoirSketch(
                capacity=self.sample_size, fields=len(self.rouge_keys) * len(_STATS)
            )
            self.add_state(
                "corpus_sample", self._reservoir.init(),
                dist_reduce_fx=self._reservoir.reduce_spec,
            )
            self.add_state("samples_total", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
            return
        self._reservoir = None
        for key in self.rouge_keys:
            for stat in _STATS:
                self.add_state(f"{key}_{stat}", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Union[str, Sequence[str]], target) -> State:
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        elif len(target) > 0 and isinstance(target[0], str):
            target = [[t] for t in target]
        results = _rouge_score_update(
            preds, target, self.rouge_keys_values, self.accumulate,
            self.stemmer, self.normalizer, self.tokenizer,
        )
        inv = {v: k for k, v in ALLOWED_ROUGE_KEYS.items()}
        if self._reservoir is not None:
            n = len(preds)
            records = np.zeros((n, self._reservoir.fields), np.float32)
            for key_val, samples in results.items():
                col0 = self.rouge_keys.index(inv[key_val]) * len(_STATS)
                for j, stat in enumerate(_STATS):
                    records[:, col0 + j] = [s[stat] for s in samples]
            keys = jnp.asarray([content_key(p) for p in preds], jnp.uint32)
            return {
                "corpus_sample": self._reservoir.insert_batch(
                    state["corpus_sample"], jnp.asarray(records), keys
                ),
                "samples_total": state["samples_total"] + n,
            }
        new = dict(state)
        for key_val, samples in results.items():
            name = inv[key_val]
            for stat in _STATS:
                vals = jnp.asarray([s[stat] for s in samples], jnp.float32)
                new[f"{name}_{stat}"] = new[f"{name}_{stat}"] + (vals,)
        return new

    def _compute(self, state: State) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        if self._reservoir is not None:
            res = self._reservoir
            sample = state["corpus_sample"]
            mask = np.asarray(res.valid_mask(sample))  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            payload = np.asarray(res.payload(sample))  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            kept = int(mask.sum())  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            total = int(state["samples_total"])  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            worst = 0.0
            for i, key in enumerate(self.rouge_keys):
                for j, stat in enumerate(_STATS):
                    col = payload[mask, i * len(_STATS) + j]
                    mean = float(col.mean()) if kept else 0.0  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
                    out[f"{key}_{stat}"] = jnp.asarray(mean, jnp.float32)
                    if total > kept:
                        worst = max(
                            worst, (total - kept) / total * max(mean, 1.0 - mean)
                        )
            # data-dependent bound on |kept-sample mean − corpus mean|: the
            # unsampled mass can pull a [0, 1] mean by at most its fraction
            # times the worst per-sample deviation; exact (0) while n <= k
            self.__dict__["_reservoir_bound"] = worst
            return out
        for key in self.rouge_keys:
            for stat in _STATS:
                vals = state[f"{key}_{stat}"]
                out[f"{key}_{stat}"] = (
                    dim_zero_cat(vals).mean() if vals else jnp.zeros(())
                )
        return out

    def _gather_approx_provenance(self) -> Optional[Dict[str, Any]]:
        """Accuracy-plane hook: reservoir provenance with the data-dependent
        sampling bound from the last ``compute()`` (0 until one has run)."""
        if self._reservoir is None:
            return None
        return {
            "source": "gather_approx",
            "kind": "reservoir",
            "capacity": self._reservoir.capacity,
            "fields": self._reservoir.fields,
            "bound": float(self.__dict__.get("_reservoir_bound", 0.0)),
        }
