"""ROUGE modular metric (reference: text/rouge.py:36-220)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class ROUGEScore(Metric):
    """ROUGE-N/L/Lsum; per-sample P/R/F stored as cat states so the sync path
    moves only tensors (reference text/rouge.py:143 stores the same).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import ROUGEScore
        >>> metric = ROUGEScore(rouge_keys='rouge1')
        >>> metric.update("the cat is on the mat", "a cat is on the mat")
        >>> round(float(metric.compute()['rouge1_fmeasure']), 4)
        0.8333
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer:
            try:
                from nltk.stem.porter import PorterStemmer  # type: ignore  # noqa: F401
            except ImportError as err:
                raise ModuleNotFoundError("Stemmer requires the `nltk` package which is not installed.") from err
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[k] for k in rouge_keys]
        self.use_stemmer = use_stemmer
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        if use_stemmer:
            from nltk.stem.porter import PorterStemmer  # type: ignore

            self.stemmer = PorterStemmer()
        else:
            self.stemmer = None

        for key in self.rouge_keys:
            for stat in ("fmeasure", "precision", "recall"):
                self.add_state(f"{key}_{stat}", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Union[str, Sequence[str]], target) -> State:
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        elif len(target) > 0 and isinstance(target[0], str):
            target = [[t] for t in target]
        results = _rouge_score_update(
            preds, target, self.rouge_keys_values, self.accumulate,
            self.stemmer, self.normalizer, self.tokenizer,
        )
        new = dict(state)
        inv = {v: k for k, v in ALLOWED_ROUGE_KEYS.items()}
        for key_val, samples in results.items():
            name = inv[key_val]
            for stat in ("fmeasure", "precision", "recall"):
                vals = jnp.asarray([s[stat] for s in samples], jnp.float32)
                new[f"{name}_{stat}"] = new[f"{name}_{stat}"] + (vals,)
        return new

    def _compute(self, state: State) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        for key in self.rouge_keys:
            for stat in ("fmeasure", "precision", "recall"):
                vals = state[f"{key}_{stat}"]
                out[f"{key}_{stat}"] = (
                    dim_zero_cat(vals).mean() if vals else jnp.zeros(())
                )
        return out
