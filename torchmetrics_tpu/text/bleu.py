"""BLEU / SacreBLEU modular metrics (reference: text/bleu.py:33, text/sacre_bleu.py:34).

Exact BLEU is already gather-free — its states are fixed-shape per-order
sums.  ``approx="reservoir"`` additionally bounds the *per-sentence* stat
rows at ``sample_size`` via a deterministic bottom-k-by-hash corpus sample
and estimates the corpus sums by reweighting the kept rows with
``total_seen / kept`` — useful when the corpus-sample provenance (which
sentences drove the score) must ship along with the value.  The stamped
data-dependent bound is the unsampled-mass fraction ``(n - k)/n`` (0 while
the corpus fits the reservoir).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from torchmetrics_tpu.sketches.reservoir import ReservoirSketch


class BLEUScore(Metric):
    """Corpus BLEU; states = per-order numerator/denominator + length sums
    (reference text/bleu.py:33-130).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> metric = BLEUScore(n_gram=2)
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.8165
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        sample_size: int = 1024,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self._tokenizer = _tokenize_fn
        if not (isinstance(sample_size, int) and sample_size >= 1):
            raise ValueError(f"Argument `sample_size` must be a positive int, got {sample_size!r}")
        #: reservoir capacity under ``approx="reservoir"`` (sentence rows kept)
        self.sample_size = sample_size
        self._install_approx_states()

    def _install_approx_states(self) -> None:
        """(Re-)register state leaves for the current ``approx`` config —
        the :meth:`~torchmetrics_tpu.core.metric.Metric.set_approx` hook."""
        if self.approx == "reservoir":
            # one row per sentence: [preds_len, target_len, numerator(n), denominator(n)]
            self._reservoir = ReservoirSketch(
                capacity=self.sample_size, fields=2 + 2 * self.n_gram
            )
            self.add_state(
                "corpus_sample", self._reservoir.init(),
                dist_reduce_fx=self._reservoir.reduce_spec,
            )
            self.add_state("samples_total", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
            return
        self._reservoir = None
        self.add_state("preds_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Union[str, Sequence[str]], target: Sequence) -> State:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        if self._reservoir is not None:
            return self._update_reservoir(state, preds_, target_)
        numerator = np.asarray(state["numerator"]).copy()  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
        denominator = np.asarray(state["denominator"]).copy()  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
        preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator,
            float(state["preds_len"]), float(state["target_len"]),  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
            self.n_gram, self._tokenizer,
        )
        return {
            "preds_len": jnp.asarray(preds_len),
            "target_len": jnp.asarray(target_len),
            "numerator": jnp.asarray(numerator),
            "denominator": jnp.asarray(denominator),
        }

    def _update_reservoir(self, state: State, preds_: list, target_: list) -> State:
        from torchmetrics_tpu.text.rouge import content_key

        n = len(preds_)
        records = np.zeros((n, self._reservoir.fields), np.float32)
        keys = np.zeros((n,), np.uint32)
        for i, (p, t) in enumerate(zip(preds_, target_)):
            num = np.zeros(self.n_gram)
            den = np.zeros(self.n_gram)
            p_len, t_len = _bleu_score_update(
                [p], [t], num, den, 0.0, 0.0, self.n_gram, self._tokenizer
            )
            records[i] = np.concatenate([[p_len, t_len], num, den])
            keys[i] = content_key(p)
        return {
            "corpus_sample": self._reservoir.insert_batch(
                state["corpus_sample"], jnp.asarray(records), jnp.asarray(keys)
            ),
            "samples_total": state["samples_total"] + n,
        }

    def _compute(self, state: State) -> Array:
        if self._reservoir is not None:
            res = self._reservoir
            sample = state["corpus_sample"]
            mask = np.asarray(res.valid_mask(sample))  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            payload = np.asarray(res.payload(sample), np.float64)  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            kept = int(mask.sum())  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            total = int(state["samples_total"])  # tmt: ignore[TMT003] -- host-side text metric: the reservoir estimate runs on host arrays
            # Horvitz–Thompson-style estimate of each corpus sum: the kept
            # rows are a deterministic uniform-over-keys sample, so every sum
            # scales by total/kept; the stamped bound is the unsampled-mass
            # fraction (0 while the corpus fits the reservoir)
            scale = (total / kept) if kept else 0.0
            self.__dict__["_reservoir_bound"] = ((total - kept) / total) if total > kept else 0.0
            sums = payload[mask].sum(axis=0) * scale
            g = self.n_gram
            return _bleu_score_compute(
                jnp.asarray(sums[0]), jnp.asarray(sums[1]),
                jnp.asarray(sums[2 : 2 + g]), jnp.asarray(sums[2 + g : 2 + 2 * g]),
                self.n_gram, self.weights, self.smooth,
            )
        return _bleu_score_compute(
            state["preds_len"], state["target_len"],
            state["numerator"], state["denominator"],
            self.n_gram, self.weights, self.smooth,
        )

    def _gather_approx_provenance(self) -> Optional[Dict[str, Any]]:
        """Accuracy-plane hook: reservoir provenance with the unsampled-mass
        bound from the last ``compute()`` (0 until one has run)."""
        if self._reservoir is None:
            return None
        return {
            "source": "gather_approx",
            "kind": "reservoir",
            "capacity": self._reservoir.capacity,
            "fields": self._reservoir.fields,
            "bound": float(self.__dict__.get("_reservoir_bound", 0.0)),
        }


class SacreBLEUScore(BLEUScore):
    """BLEU with canonical tokenization (reference text/sacre_bleu.py:34-140).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.7598
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {list(AVAILABLE_TOKENIZERS)}")
        # public mirrors fingerprint the tokenizer config (TMT011): without
        # them two instances differing only in `tokenize` share a cache key
        self.tokenize = tokenize
        self.lowercase = lowercase
        self._tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
