"""BLEU / SacreBLEU modular metrics (reference: text/bleu.py:33, text/sacre_bleu.py:34)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer


class BLEUScore(Metric):
    """Corpus BLEU; states = per-order numerator/denominator + length sums
    (reference text/bleu.py:33-130).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> metric = BLEUScore(n_gram=2)
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.8165
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self._tokenizer = _tokenize_fn

        self.add_state("preds_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Union[str, Sequence[str]], target: Sequence) -> State:
        preds_ = [preds] if isinstance(preds, str) else list(preds)
        target_ = [[t] if isinstance(t, str) else list(t) for t in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        numerator = np.asarray(state["numerator"]).copy()  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
        denominator = np.asarray(state["denominator"]).copy()  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
        preds_len, target_len = _bleu_score_update(
            preds_, target_, numerator, denominator,
            float(state["preds_len"]), float(state["target_len"]),  # tmt: ignore[TMT003] -- host-side text metric: n-gram counting runs on host arrays
            self.n_gram, self._tokenizer,
        )
        return {
            "preds_len": jnp.asarray(preds_len),
            "target_len": jnp.asarray(target_len),
            "numerator": jnp.asarray(numerator),
            "denominator": jnp.asarray(denominator),
        }

    def _compute(self, state: State) -> Array:
        return _bleu_score_compute(
            state["preds_len"], state["target_len"],
            state["numerator"], state["denominator"],
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """BLEU with canonical tokenization (reference text/sacre_bleu.py:34-140).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.7598
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {list(AVAILABLE_TOKENIZERS)}")
        # public mirrors fingerprint the tokenizer config (TMT011): without
        # them two instances differing only in `tokenize` share a cache key
        self.tokenize = tokenize
        self.lowercase = lowercase
        self._tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
