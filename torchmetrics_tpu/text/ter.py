"""TER modular metric (reference: text/ter.py:29-160)."""

from __future__ import annotations

from typing import Any, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.ter import (
    _compute_ter_score_from_statistics,
    _corpus_statistics,
    _TercomTokenizer,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class TranslationEditRate(Metric):
    """Corpus TER; state = total edits + total reference length, sum-reduced
    (reference text/ter.py:29).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.1667
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        for name, val in (
            ("normalize", normalize), ("no_punctuation", no_punctuation),
            ("lowercase", lowercase), ("asian_support", asian_support),
        ):
            if not isinstance(val, bool):
                raise ValueError(f"`{name}` must be a bool, got {val!r}.")
        # public mirrors fingerprint the tokenizer config (TMT011)
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support
        self._tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", jnp.zeros(()), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def _update(
        self, state: State, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> State:
        num_edits, tgt_length, per_sentence = _corpus_statistics(preds, target, self._tokenizer)
        new = {
            "total_num_edits": state["total_num_edits"] + num_edits,
            "total_tgt_length": state["total_tgt_length"] + tgt_length,
        }
        if self.return_sentence_level_score:
            new["sentence_ter"] = state["sentence_ter"] + (jnp.asarray(per_sentence, jnp.float32),)
        return new

    def _compute(self, state: State) -> Union[Array, Tuple[Array, Array]]:
        score = jnp.asarray(
            _compute_ter_score_from_statistics(
                float(state["total_num_edits"]), float(state["total_tgt_length"])  # tmt: ignore[TMT003] -- host-side text metric: TER statistics are host numbers
            ),
            jnp.float32,
        )
        if self.return_sentence_level_score:
            return score, dim_zero_cat(state["sentence_ter"])
        return score
