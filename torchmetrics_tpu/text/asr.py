"""ASR error-rate modular metrics: WER/CER/MER/WIL/WIP/EditDistance.

Reference: text/{wer.py:28, cer.py:28, mer.py:28, wil.py:28, wip.py:28,
edit.py:29}.  All keep scalar sum states; EditDistance with
``reduction='none'`` keeps a cat state of per-sample distances.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.asr import (
    _cer_update,
    _edit_update,
    _mer_update,
    _wer_update,
    _wil_wip_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _ErrorRateMetric(Metric):
    """Base for (errors, total) ratio metrics."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    _update_fn = None  # set by subclass

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Union[str, List[str]], target: Union[str, List[str]]) -> State:
        errors, total = type(self)._update_fn(preds, target)
        return {"errors": state["errors"] + errors, "total": state["total"] + total}

    def _compute(self, state: State) -> Array:
        return state["errors"] / state["total"]


class WordErrorRate(_ErrorRateMetric):
    """WER (reference text/wer.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> round(float(metric.compute()), 4)
        0.25
    """

    _update_fn = staticmethod(_wer_update)


class CharErrorRate(_ErrorRateMetric):
    """CER (reference text/cer.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import CharErrorRate
        >>> metric = CharErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> round(float(metric.compute()), 4)
        0.381
    """

    _update_fn = staticmethod(_cer_update)


class MatchErrorRate(_ErrorRateMetric):
    """MER (reference text/mer.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import MatchErrorRate
        >>> metric = MatchErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> round(float(metric.compute()), 4)
        0.25
    """

    _update_fn = staticmethod(_mer_update)


class _WordInfoBase(Metric):
    is_differentiable = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("hits", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Union[str, List[str]], target: Union[str, List[str]]) -> State:
        hits, tt, pt = _wil_wip_update(preds, target)
        return {
            "hits": state["hits"] + hits,
            "target_total": state["target_total"] + tt,
            "preds_total": state["preds_total"] + pt,
        }

    def _wip(self, state: State) -> Array:
        return (state["hits"] / state["target_total"]) * (state["hits"] / state["preds_total"])


class WordInfoPreserved(_WordInfoBase):
    """WIP (reference text/wip.py:28)."""

    higher_is_better = True

    def _compute(self, state: State) -> Array:
        return self._wip(state)


class WordInfoLost(_WordInfoBase):
    """WIL (reference text/wil.py:28)."""

    higher_is_better = False

    def _compute(self, state: State) -> Array:
        return 1.0 - self._wip(state)


class EditDistance(Metric):
    """Char-level Levenshtein (reference text/edit.py:29)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, substitution_cost: int = 1, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        if reduction not in ("mean", "sum", "none", None):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.substitution_cost = substitution_cost
        self.reduction = reduction

        if reduction in ("none", None):
            self.add_state("values", [], dist_reduce_fx="cat")
        else:
            # int32: edit distances and sentence counts are integers; float32
            # sums stagnate at 2**24 (TMT014 horizon analysis)
            self.add_state("values", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))
            self.add_state("count", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))

    def _update(self, state: State, preds: Union[str, List[str]], target: Union[str, List[str]]) -> State:
        dists = _edit_update(preds, target, self.substitution_cost)
        if self.reduction in ("none", None):
            return {"values": state["values"] + (jnp.asarray(dists, jnp.int32),)}
        return {
            "values": state["values"] + int(sum(dists)),  # tmt: ignore[TMT003] -- host-side text metric: edit distances are Python numbers from strings
            "count": state["count"] + len(dists),
        }

    def _compute(self, state: State) -> Array:
        if self.reduction in ("none", None):
            return dim_zero_cat(state["values"]) if state["values"] else jnp.zeros(0)
        if self.reduction == "sum":
            return state["values"]
        return state["values"] / jnp.maximum(state["count"], 1.0)
