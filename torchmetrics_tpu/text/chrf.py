"""chrF modular metric (reference: text/chrf.py:52-230)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.chrf import _ChrFStats, _chrf_score_update, _fscore
from torchmetrics_tpu.utilities.data import dim_zero_cat


class CHRFScore(Metric):
    """chrF/chrF++; state = six per-order count arrays, sum-reduced
    (reference text/chrf.py:52 keeps the same counts as dict states).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> metric = CHRFScore()
        >>> metric.update(["the cat is on the mat"], [["a cat is on the mat"]])
        >>> round(float(metric.compute()), 4)
        0.864
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("matching_char", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("matching_word", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("preds_char", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("preds_word", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("target_char", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("target_word", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf", [], dist_reduce_fx="cat")

    def _update(
        self, state: State, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]
    ) -> State:
        stats = _ChrFStats(self.n_char_order, self.n_word_order)
        sentence_scores: Optional[List[float]] = [] if self.return_sentence_level_score else None
        _chrf_score_update(
            preds, target, stats, self.n_char_order, self.n_word_order,
            self.beta, self.lowercase, self.whitespace, sentence_scores,
        )
        new = {
            "matching_char": state["matching_char"] + jnp.asarray(stats.matching_char),
            "matching_word": state["matching_word"] + jnp.asarray(stats.matching_word),
            "preds_char": state["preds_char"] + jnp.asarray(stats.preds_char),
            "preds_word": state["preds_word"] + jnp.asarray(stats.preds_word),
            "target_char": state["target_char"] + jnp.asarray(stats.target_char),
            "target_word": state["target_word"] + jnp.asarray(stats.target_word),
        }
        if self.return_sentence_level_score:
            new["sentence_chrf"] = state["sentence_chrf"] + (jnp.asarray(sentence_scores, jnp.float32),)
        return new

    def _compute(self, state: State) -> Union[Array, Tuple[Array, Array]]:
        corpus = jnp.asarray(
            _fscore(
                np.asarray(state["matching_char"]), np.asarray(state["matching_word"]),  # tmt: ignore[TMT003] -- host-side text metric: chrF statistics are host numbers
                np.asarray(state["preds_char"]), np.asarray(state["preds_word"]),  # tmt: ignore[TMT003] -- host-side text metric: chrF statistics are host numbers
                np.asarray(state["target_char"]), np.asarray(state["target_word"]),  # tmt: ignore[TMT003] -- host-side text metric: chrF statistics are host numbers
                float(self.n_char_order + self.n_word_order), self.beta,  # tmt: ignore[TMT003] -- host-side text metric: chrF statistics are host numbers
            ),
            jnp.float32,
        )
        if self.return_sentence_level_score:
            return corpus, dim_zero_cat(state["sentence_chrf"])
        return corpus
