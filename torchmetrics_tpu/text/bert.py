"""BERTScore modular metric (reference: text/bert.py:54-260).

Stores tokenized input_ids/attention_mask as cat states — strings never enter
the sync path (reference text/bert.py:194-197, the precedent SURVEY.md
§2.4-text calls out).  The embedding model is pluggable.

Example::

    >>> from torchmetrics_tpu.text import BERTScore
    >>> metric = BERTScore(verbose=False)
    >>> metric.update(['the cat sat'], ['the cat sat'])
    >>> {k: round(float(v[0]), 4) for k, v in sorted(metric.compute().items())}
    {'f1': 1.0, 'precision': 1.0, 'recall': 1.0}
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.bert import (
    _bert_score_from_embeddings,
    _compute_idf,
    _idf_weights,
    _process_special_tokens_mask,
    _reject_unsupported_bert_args,
    resolve_embedder,
)


class BERTScore(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        truncation: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _reject_unsupported_bert_args(all_layers, rescale_with_baseline)
        self.idf = idf
        self.return_hash = return_hash
        self.embed_fn, self.tokenizer, self._zero_special, self.model_name_or_path = resolve_embedder(
            model_name_or_path, num_layers, max_length, truncation=truncation,
            model=model, user_tokenizer=user_tokenizer, user_forward_fn=user_forward_fn,
        )

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _update(
        self, state: State, preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
    ) -> State:
        preds_l = [preds] if isinstance(preds, str) else list(preds)
        target_l = [target] if isinstance(target, str) else list(target)
        if len(preds_l) != len(target_l):
            raise ValueError("Number of predicted and reference sententes must be the same!")
        p = self.tokenizer(preds_l)
        t = self.tokenizer(target_l)
        return {
            "preds_input_ids": state["preds_input_ids"] + (jnp.asarray(p["input_ids"]),),
            "preds_attention_mask": state["preds_attention_mask"] + (jnp.asarray(p["attention_mask"]),),
            "target_input_ids": state["target_input_ids"] + (jnp.asarray(t["input_ids"]),),
            "target_attention_mask": state["target_attention_mask"] + (jnp.asarray(t["attention_mask"]),),
        }

    @staticmethod
    def _pad_cat(chunks: Sequence[Array]) -> np.ndarray:
        t_max = max(c.shape[1] for c in chunks)
        rows = [np.pad(np.asarray(c), ((0, 0), (0, t_max - c.shape[1]))) for c in chunks]
        return np.concatenate(rows, axis=0)

    def _compute(self, state: State) -> Dict[str, Array]:
        if not state["preds_input_ids"]:
            return {"precision": jnp.zeros(0), "recall": jnp.zeros(0), "f1": jnp.zeros(0)}
        p_ids = self._pad_cat(state["preds_input_ids"])
        p_mask = self._pad_cat(state["preds_attention_mask"])
        t_ids = self._pad_cat(state["target_input_ids"])
        t_mask = self._pad_cat(state["target_attention_mask"])

        t_max = max(p_ids.shape[1], t_ids.shape[1])
        p_ids = np.pad(p_ids, ((0, 0), (0, t_max - p_ids.shape[1])))
        p_mask = np.pad(p_mask, ((0, 0), (0, t_max - p_mask.shape[1])))
        t_ids = np.pad(t_ids, ((0, 0), (0, t_max - t_ids.shape[1])))
        t_mask = np.pad(t_mask, ((0, 0), (0, t_max - t_mask.shape[1])))

        pred_emb = jnp.asarray(self.embed_fn(jnp.asarray(p_ids), jnp.asarray(p_mask)))
        tgt_emb = jnp.asarray(self.embed_fn(jnp.asarray(t_ids), jnp.asarray(t_mask)))

        if self._zero_special:  # tmt: ignore[TMT011] -- produced by the same deterministic resolve_embedder call whose model_name_or_path result is mirrored publicly; same fingerprint implies same _zero_special
            p_mask = _process_special_tokens_mask(p_mask)
            t_mask = _process_special_tokens_mask(t_mask)

        pw = tw = None
        if self.idf:
            idf_map = _compute_idf(t_ids, t_mask)
            pw = jnp.asarray(_idf_weights(p_ids, p_mask, idf_map))
            tw = jnp.asarray(_idf_weights(t_ids, t_mask, idf_map))

        precision, recall, f1 = _bert_score_from_embeddings(
            pred_emb, jnp.asarray(p_mask), tgt_emb, jnp.asarray(t_mask), pw, tw
        )
        out: Dict[str, Any] = {"precision": precision, "recall": recall, "f1": f1}
        if self.return_hash:
            out["hash"] = f"tpu_bert_score(model={self.model_name_or_path or 'user-model'})"
        return out
