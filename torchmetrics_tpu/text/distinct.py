"""Distinct n-gram ratio over token-id streams.

No reference-torchmetrics counterpart — this is the repo's cardinality
dogfood metric (ROADMAP Open item 1): "how many distinct n-grams did the
model generate" is the canonical unbounded-``cat``-state problem, since the
exact answer needs every n-gram kept until ``compute``.  Two modes:

* exact (default): ``cat`` state of ``(windows, n)`` int32 n-gram rows;
  ``compute`` sorts lexicographically and counts row changes — exact, but
  state (and its cross-device ``all_gather``) grows with every token.
* ``approx="sketch"``: a fixed :class:`~torchmetrics_tpu.sketches.HyperLogLog`
  register array (merge/sync = elementwise ``pmax``) plus a scalar window
  counter — bounded state, documented ``~1.04/sqrt(m)`` relative error on
  the distinct count.

Both modes share one windowing/masking path, and invalid windows (any token
== ``ignore_index``) are dropped statically: exact mode rewrites them to a
sentinel row sorted last, sketch mode zeroes their HLL rank.

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.text import DistinctNGrams
    >>> metric = DistinctNGrams(ngram=2)
    >>> metric.update(jnp.asarray([[3, 5, 3, 5, 3]]))
    >>> round(float(metric.compute()), 4)  # windows: (3,5) (5,3) (3,5) (5,3)
    0.5
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.utilities.data import dim_zero_cat

#: sentinel token for invalid windows in the exact cat state — larger than
#: any real int32 token id once compared as int64 column keys.  A plain int
#: (not a materialized ``jnp.int32`` array): creating a device array at
#: import time would initialize the JAX backend before callers — notably
#: ``python -m torchmetrics_tpu.analysis --audit-all`` — can configure the
#: device topology via XLA_FLAGS.
_SENTINEL = -1


class DistinctNGrams(Metric):
    """Fraction of generated n-grams that are distinct (type/token ratio).

    Args:
        ngram: window length (1 = distinct tokens).
        ignore_index: token id to treat as padding; windows containing it
            are excluded from both the distinct and total counts.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    #: HyperLogLog when ``approx="sketch"`` replaced the cat state
    _hll = None

    def __init__(self, ngram: int = 1, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(ngram, int) and ngram >= 1):
            raise ValueError(f"Argument `ngram` expected to be an integer >= 1, but got {ngram}")
        self.ngram = ngram
        self.ignore_index = ignore_index
        if self.approx == "sketch":
            from torchmetrics_tpu.sketches import HyperLogLog

            self._hll = HyperLogLog.for_error(self.approx_error)
            self.add_state("registers", self._hll.init(), dist_reduce_fx=self._hll.reduce_spec)
        else:
            self.add_state("ngrams", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    # ------------------------------------------------------------- windowing
    def _windows(self, tokens: Array):
        """``(rows, n)`` stacked n-gram windows + ``(rows,)`` validity mask."""
        tokens = jnp.atleast_2d(jnp.asarray(tokens, jnp.int32))
        if tokens.shape[-1] < self.ngram:
            raise ValueError(
                f"DistinctNGrams(ngram={self.ngram}) needs sequences of at least {self.ngram} "
                f"tokens, got shape {tokens.shape}"
            )
        span = tokens.shape[-1] - self.ngram + 1
        win = jnp.stack([tokens[..., k : k + span] for k in range(self.ngram)], axis=-1)
        win = win.reshape(-1, self.ngram)  # (rows, n)
        if self.ignore_index is None:
            valid = jnp.ones((win.shape[0],), bool)
        else:
            valid = jnp.all(win != jnp.int32(self.ignore_index), axis=-1)
        return win, valid

    def _keys(self, windows: Array) -> Array:
        """One uint32 key per window: chained avalanche mix over the tokens."""
        from torchmetrics_tpu.sketches import mix32

        h = jnp.full((windows.shape[0],), 0, jnp.uint32)
        for k in range(self.ngram):
            h = mix32(windows[:, k].astype(jnp.uint32) + h, jnp.uint32(0x9E3779B9) * jnp.uint32(k + 1))
        return h

    # ---------------------------------------------------------------- update
    def _update(self, state: State, preds: Array) -> State:
        win, valid = self._windows(preds)
        total = state["total"] + valid.sum()
        if self._hll is not None:
            return {"registers": self._hll.insert_batch(state["registers"], self._keys(win), mask=valid), "total": total}
        win = jnp.where(valid[:, None], win, _SENTINEL)
        return {"ngrams": tuple(state["ngrams"]) + (win,), "total": total}

    # --------------------------------------------------------------- compute
    def _compute(self, state: State) -> Array:
        total = jnp.maximum(state["total"], 1.0)
        if self._hll is not None:
            return jnp.clip(self._hll.estimate(state["registers"]) / total, 0.0, 1.0)
        rows = dim_zero_cat(state["ngrams"])  # (rows, n)
        # lexicographic sort via one int64 rank per column pass (static
        # shapes; last key first, stable) — sentinel rows group together
        order = jnp.arange(rows.shape[0])
        for col in range(rows.shape[1] - 1, -1, -1):
            order = order[jnp.argsort(rows[order, col], stable=True)]
        srt = rows[order]
        valid = srt[:, 0] != _SENTINEL
        changed = jnp.concatenate([jnp.ones((1,), bool), jnp.any(srt[1:] != srt[:-1], axis=-1)])
        distinct = jnp.sum(changed & valid)
        return distinct / total
