"""EED modular metric (reference: text/eed.py:28-140).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.text import ExtendedEditDistance
    >>> metric = ExtendedEditDistance()
    >>> metric.update(['this is the prediction'], ['this is the reference'])
    >>> round(float(metric.compute()), 4)
    0.3835
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.eed import _eed_update
from torchmetrics_tpu.utilities.data import dim_zero_cat


class ExtendedEditDistance(Metric):
    """Corpus EED = mean of per-sentence scores; state = cat of scores
    (reference text/eed.py:28 keeps `sentence_eed` list state)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(val, float) or val < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def _update(
        self, state: State, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> State:
        scores: List[float] = []
        _eed_update(preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion, scores)
        return {"sentence_eed": state["sentence_eed"] + (jnp.asarray(scores, jnp.float32),)}

    def _compute(self, state: State) -> Union[Array, Tuple[Array, Array]]:
        if not state["sentence_eed"]:
            return jnp.zeros(())
        scores = dim_zero_cat(state["sentence_eed"])
        avg = scores.mean()
        if self.return_sentence_level_score:
            return avg, scores
        return avg
