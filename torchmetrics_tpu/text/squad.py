"""SQuAD modular metric (reference: text/squad.py:34-120).
Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.text import SQuAD
    >>> metric = SQuAD()
    >>> preds = [{'prediction_text': '1976', 'id': '1'}]
    >>> target = [{'answers': {'answer_start': [97], 'text': ['1976']}, 'id': '1'}]
    >>> metric.update(preds, target)
    >>> {k: float(v) for k, v in sorted(metric.compute().items())}
    {'exact_match': 100.0, 'f1': 100.0}
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)


class SQuAD(Metric):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: PREDS_TYPE, target: TARGETS_TYPE) -> State:
        preds_dict, articles = _squad_input_check(preds, target)
        f1, em, total = _squad_update(preds_dict, articles)
        return {
            "f1_score": state["f1_score"] + f1,
            "exact_match": state["exact_match"] + em,
            "total": state["total"] + total,
        }

    def _compute(self, state: State) -> Dict[str, Array]:
        return _squad_compute(state["f1_score"], state["exact_match"], state["total"])
