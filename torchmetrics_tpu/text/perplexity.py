"""Perplexity modular metric (reference: text/perplexity.py:28-110).

The one text metric whose ``update`` is fully jittable — construct with
``jit=True`` (or call ``update_state`` inside a pjit'd eval step) and the
accumulation fuses into the step graph.

Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.text import Perplexity
    >>> metric = Perplexity()
    >>> logits = jnp.log(jnp.asarray([[[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]]))
    >>> metric.update(logits, jnp.asarray([[0, 1]]))
    >>> round(float(metric.compute()), 4)
    1.3363
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update


class Perplexity(Metric):
    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        total, count = _perplexity_update(preds, target, self.ignore_index)
        return {
            "total_log_probs": state["total_log_probs"] + total,
            "count": state["count"] + count,
        }

    def _compute(self, state: State) -> Array:
        return _perplexity_compute(state["total_log_probs"], state["count"])
