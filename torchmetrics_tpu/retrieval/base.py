"""Retrieval metric base — grouped-by-query metrics over accumulated triples.

Reference: /root/reference/src/torchmetrics/retrieval/base.py:43-200
(``RetrievalMetric``).  The reference splits the concatenated arrays per query
and runs a Python loop; here ``compute`` hands the flat arrays to the
vectorized sort+segment kernels (functional/retrieval/kernels.py) and gets all
per-query scores in one XLA call — empty-query policy and aggregation are then
cheap masked reductions.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.retrieval.kernels import RankedGroups, rank_groups
from torchmetrics_tpu.utilities.data import dim_zero_cat

_AGG_OPTIONS = ("mean", "median", "min", "max")


def _retrieval_aggregate(
    values: Array,
    aggregation: Union[str, Callable] = "mean",
    axis: Optional[int] = None,
) -> Array:
    """Aggregate per-query scores (reference base.py:26-41)."""
    if aggregation == "mean":
        return values.mean() if axis is None else values.mean(axis=axis)
    if aggregation == "median":
        return jnp.median(values) if axis is None else jnp.median(values, axis=axis)
    if aggregation == "min":
        return values.min() if axis is None else values.min(axis=axis)
    if aggregation == "max":
        return values.max() if axis is None else values.max(axis=axis)
    return aggregation(values, axis=axis)


class RetrievalMetric(Metric):
    """Base for metrics grouped by query index.

    Accepts ``update(preds, target, indexes)``; scores are computed per query
    then aggregated.  ``empty_target_action`` controls queries with no positive
    target: ``'neg'`` → 0, ``'pos'`` → 1, ``'skip'`` → dropped, ``'error'`` →
    raise (reference base.py:105-132).
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    allow_non_binary_target = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation: Union[str, Callable] = "mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if empty_target_action not in ("error", "skip", "neg", "pos"):
            raise ValueError(
                f"Argument `empty_target_action` received a wrong value `{empty_target_action}`."
            )
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in _AGG_OPTIONS or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom "
                f"callable function which takes tensor of values, but got {aggregation}."
            )
        self.aggregation = aggregation

        self.add_state("indexes", [], dist_reduce_fx="cat")
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _check_inputs(self, preds: Array, target: Array, indexes: Array) -> tuple:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        preds = jnp.ravel(jnp.asarray(preds)).astype(jnp.float32)
        target = jnp.ravel(jnp.asarray(target))
        indexes = jnp.ravel(jnp.asarray(indexes))
        if not (preds.shape == target.shape == indexes.shape):
            raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
        # data-dependent validation/filtering happens eagerly only; under a
        # trace (e.g. sharded_update inside shard_map) shapes are static and
        # values unavailable, so these host checks are skipped
        tracing = isinstance(target, jax.core.Tracer)
        if self.ignore_index is not None:
            if tracing:
                raise TorchMetricsUserError(
                    "`ignore_index` filtering changes shapes and cannot run inside a traced "
                    "update; filter the inputs before the jitted step instead."
                )
            keep = np.asarray(target) != self.ignore_index
            preds, target, indexes = preds[keep], target[keep], indexes[keep]
        if not self.allow_non_binary_target and not tracing:
            tnp = np.asarray(target)
            if ((tnp != 0) & (tnp != 1)).any():
                raise ValueError("`target` must contain binary values")
        return preds, target.astype(jnp.float32), indexes

    def _update(self, state: State, preds: Array, target: Array, indexes: Array) -> State:
        preds, target, indexes = self._check_inputs(preds, target, indexes)
        return {
            "indexes": state["indexes"] + (indexes,),
            "preds": state["preds"] + (preds,),
            "target": state["target"] + (target,),
        }

    # subclass hook: per-group scores from the ranked view
    def _metric_grouped(self, rg: RankedGroups) -> Array:
        raise NotImplementedError

    def _empty_mask(self, rg: RankedGroups) -> Array:
        """True for queries hit by ``empty_target_action`` (no positive target)."""
        return rg.n_rel == 0

    def _compute(self, state: State) -> Array:
        if not state["preds"]:
            return jnp.zeros(())
        preds = dim_zero_cat(state["preds"])
        target = dim_zero_cat(state["target"])
        indexes = dim_zero_cat(state["indexes"])
        rg = rank_groups(preds, target, indexes)
        scores = self._metric_grouped(rg)
        empty = self._empty_mask(rg)
        return self._aggregate_scores(scores, empty)

    def _aggregate_scores(self, scores: Array, empty: Array) -> Array:
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "skip":
            keep = np.asarray(~empty)
            scores = scores[keep]
            if scores.size == 0:
                return jnp.zeros(())
        elif self.empty_target_action == "pos":
            scores = jnp.where(empty, 1.0, scores)
        else:  # neg
            scores = jnp.where(empty, 0.0, scores)
        return _retrieval_aggregate(scores, self.aggregation)
