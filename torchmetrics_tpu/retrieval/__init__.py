"""Retrieval metrics (reference: src/torchmetrics/retrieval/__init__.py)."""

from torchmetrics_tpu.retrieval.base import RetrievalMetric
from torchmetrics_tpu.retrieval.metrics import (
    RetrievalAUROC,
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRPrecision,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
)

__all__ = [
    "RetrievalMetric",
    "RetrievalAUROC",
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRPrecision",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
]
