"""Modular retrieval metrics.

Reference classes: /root/reference/src/torchmetrics/retrieval/{average_precision
.py:28, fall_out.py:29, hit_rate.py:28, ndcg.py:28, precision.py:28, r_precision
.py:28, recall.py:28, reciprocal_rank.py:28, auroc.py:30,
precision_recall_curve.py:63,296}.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.core.metric import State
from torchmetrics_tpu.functional.retrieval.kernels import (
    RankedGroups,
    grouped_auroc,
    grouped_average_precision,
    grouped_fall_out,
    grouped_hit_rate,
    grouped_ndcg,
    grouped_precision,
    grouped_precision_recall_curve,
    grouped_r_precision,
    grouped_recall,
    grouped_reciprocal_rank,
    rank_groups,
)
from torchmetrics_tpu.functional.retrieval.kernels import _check_top_k as _validate_top_k
from torchmetrics_tpu.retrieval.base import RetrievalMetric, _retrieval_aggregate
from torchmetrics_tpu.utilities.data import dim_zero_cat


class RetrievalMAP(RetrievalMetric):
    """Mean Average Precision (reference retrieval/average_precision.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMAP
        >>> metric = RetrievalMAP()
        >>> metric.update(jnp.asarray([0.2, 0.3, 0.5, 0.1]), jnp.asarray([0, 1, 0, 1]), jnp.asarray([0, 0, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.75
    """

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_average_precision(rg, self.top_k)


class RetrievalMRR(RetrievalMetric):
    """Mean Reciprocal Rank (reference retrieval/reciprocal_rank.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalMRR
        >>> metric = RetrievalMRR()
        >>> metric.update(jnp.asarray([0.2, 0.3, 0.5, 0.1]), jnp.asarray([0, 1, 0, 1]), jnp.asarray([0, 0, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.75
    """

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_reciprocal_rank(rg, self.top_k)


class RetrievalPrecision(RetrievalMetric):
    """Precision@k (reference retrieval/precision.py:28)."""

    def __init__(
        self, top_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.top_k = top_k
        self.adaptive_k = adaptive_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_precision(rg, self.top_k, self.adaptive_k)


class RetrievalRecall(RetrievalMetric):
    """Recall@k (reference retrieval/recall.py:28)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_recall(rg, self.top_k)


class RetrievalHitRate(RetrievalMetric):
    """HitRate@k (reference retrieval/hit_rate.py:28)."""

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_hit_rate(rg, self.top_k)


class RetrievalFallOut(RetrievalMetric):
    """FallOut@k; lower is better; empty = queries with no NEGATIVE target
    (reference retrieval/fall_out.py:29, compute override :136)."""

    higher_is_better = False

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_fall_out(rg, self.top_k)

    def _empty_mask(self, rg: RankedGroups) -> Array:
        return (rg.sizes - rg.n_rel) == 0


class RetrievalRPrecision(RetrievalMetric):
    """R-Precision (reference retrieval/r_precision.py:28)."""

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        return grouped_r_precision(rg)


class RetrievalNormalizedDCG(RetrievalMetric):
    """NDCG@k; allows graded (non-binary) relevance (reference retrieval/ndcg.py:28).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.retrieval import RetrievalNormalizedDCG
        >>> metric = RetrievalNormalizedDCG()
        >>> metric.update(jnp.asarray([0.2, 0.3, 0.5, 0.1]), jnp.asarray([0, 1, 0, 1]), jnp.asarray([0, 0, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.8155
    """

    allow_non_binary_target = True

    def __init__(self, top_k: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        self.top_k = top_k

    def _compute(self, state: State) -> Array:
        if not state["preds"]:
            return jnp.zeros(())
        preds = dim_zero_cat(state["preds"])
        target = dim_zero_cat(state["target"])
        indexes = dim_zero_cat(state["indexes"])
        ndcg, n_rel = grouped_ndcg(preds, target, indexes, self.top_k)
        return self._aggregate_scores(ndcg, n_rel == 0)


class RetrievalAUROC(RetrievalMetric):
    """Per-query AUROC over retrieved docs (reference retrieval/auroc.py:30)."""

    def __init__(
        self, top_k: Optional[int] = None, max_fpr: Optional[float] = None, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        _validate_top_k(top_k)
        if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
            raise ValueError(f"Argument `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")
        self.top_k = top_k
        self.max_fpr = max_fpr

    def _metric_grouped(self, rg: RankedGroups) -> Array:
        if self.max_fpr is not None:
            # partial AUC needs the per-query ROC curve; delegate per group
            from torchmetrics_tpu.functional.classification.auroc import binary_auroc

            gid = np.asarray(rg.gid)
            p, t = np.asarray(rg.preds), np.asarray(rg.target)
            vals = []
            for g in range(rg.num_groups):
                sel = gid == g
                pg, tg = p[sel], t[sel]
                if self.top_k is not None:
                    pg, tg = pg[: self.top_k], tg[: self.top_k]
                if tg.sum() == 0 or tg.sum() == len(tg):
                    vals.append(0.0)
                else:
                    vals.append(float(binary_auroc(jnp.asarray(pg), jnp.asarray(tg, dtype=jnp.int32), max_fpr=self.max_fpr)))
            return jnp.asarray(vals, dtype=jnp.float32)
        return grouped_auroc(rg, self.top_k)


class RetrievalPrecisionRecallCurve(RetrievalMetric):
    """Averaged precision/recall at k=1..max_k across queries
    (reference retrieval/precision_recall_curve.py:63)."""

    def __init__(
        self, max_k: Optional[int] = None, adaptive_k: bool = False, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if max_k is not None and not (isinstance(max_k, int) and max_k > 0):
            raise ValueError("`max_k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.max_k = max_k
        self.adaptive_k = adaptive_k

    def _compute(self, state: State) -> Tuple[Array, Array, Array]:
        if not state["preds"]:
            k = self.max_k or 1
            return jnp.zeros(k), jnp.zeros(k), jnp.arange(1, k + 1)
        preds = dim_zero_cat(state["preds"])
        target = dim_zero_cat(state["target"])
        indexes = dim_zero_cat(state["indexes"])
        rg = rank_groups(preds, target, indexes)
        max_k = self.max_k if self.max_k is not None else int(rg.sizes.max())  # tmt: ignore[TMT003] -- host-side compute: ragged per-query grouping is data-dependent
        prec, rec, topk = grouped_precision_recall_curve(rg, max_k, self.adaptive_k)
        empty = rg.n_rel == 0
        if self.empty_target_action == "error" and bool(empty.any()):  # tmt: ignore[TMT003] -- host-side compute: empty_target_action='error' must raise eagerly
            raise ValueError("`compute` method was provided with a query with no positive target.")
        if self.empty_target_action == "skip":
            keep = np.asarray(~empty)  # tmt: ignore[TMT003] -- host-side compute: boolean row filter over ragged groups
            prec, rec = prec[keep], rec[keep]
        else:
            fill = 1.0 if self.empty_target_action == "pos" else 0.0
            prec = jnp.where(empty[:, None], fill, prec)
            rec = jnp.where(empty[:, None], fill, rec)
        if prec.shape[0] == 0:
            return jnp.zeros(max_k), jnp.zeros(max_k), topk
        return (
            _retrieval_aggregate(prec, self.aggregation, axis=0),
            _retrieval_aggregate(rec, self.aggregation, axis=0),
            topk,
        )


class RetrievalRecallAtFixedPrecision(RetrievalPrecisionRecallCurve):
    """Max recall with precision >= min_precision, plus the k achieving it
    (reference retrieval/precision_recall_curve.py:296, helper :36-60)."""

    def __init__(self, min_precision: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not (isinstance(min_precision, float) and 0.0 <= min_precision <= 1.0):
            raise ValueError("`min_precision` has to be a float between 0 and 1")
        self.min_precision = min_precision

    def _compute(self, state: State) -> Tuple[Array, Array]:
        precision, recall, top_k = super()._compute(state)
        p, r, k = np.asarray(precision), np.asarray(recall), np.asarray(top_k)  # tmt: ignore[TMT003] -- host-side compute: curve search over ragged groups
        ok = p >= self.min_precision
        if not ok.any():
            return jnp.asarray(0.0), jnp.asarray(k[-1] if k.size else 0)
        pairs = sorted(zip(r[ok].tolist(), k[ok].tolist()))  # tmt: ignore[TMT003] -- host-side compute: curve search over ragged groups
        best_r, best_k = pairs[-1]
        return jnp.asarray(best_r, dtype=jnp.float32), jnp.asarray(int(best_k))  # tmt: ignore[TMT003] -- host-side compute: curve search over ragged groups
