"""Input/behavior check helpers.

Reference: utilities/checks.py:636-740 (`check_forward_full_state_property`) —
the empirical tool that tests whether ``full_state_update=False`` is safe for
a metric class and times both paths.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_info


def check_forward_full_state_property(
    metric_class: type,
    init_args: Optional[Dict[str, Any]] = None,
    input_args: Optional[Dict[str, Any]] = None,
    num_update_to_compare: int = 10,
    reps: int = 3,
) -> None:
    """Empirically check that full_state_update=False matches True and time both.

    Instantiates the metric twice with ``full_state_update`` overridden to
    True/False, runs ``forward`` ``num_update_to_compare`` times with
    ``input_args`` on each, and asserts every batch value matches; then prints
    simple wall-clock timings (reference utilities/checks.py:636-740).
    """
    init_args = init_args or {}
    input_args = input_args or {}

    class FullState(metric_class):  # type: ignore[misc, valid-type]
        full_state_update = True

    class PartialState(metric_class):  # type: ignore[misc, valid-type]
        full_state_update = False

    full = FullState(**init_args)
    partial_state = PartialState(**init_args)

    for i in range(num_update_to_compare):
        out1 = full(**input_args)
        out2 = partial_state(**input_args)
        if not np.allclose(np.asarray(out1), np.asarray(out2), atol=1e-6, equal_nan=True):
            raise RuntimeError(
                f"The metric {metric_class.__name__} cannot safely set `full_state_update=False`: "
                f"forward outputs diverge on update {i}: {out1} vs {out2}."
            )
    # the accumulated states are where the two paths can actually diverge
    # (update-twice vs compute-batch-then-merge) — compare final compute()
    res1, res2 = full.compute(), partial_state.compute()
    if not np.allclose(np.asarray(res1), np.asarray(res2), atol=1e-6, equal_nan=True):
        raise RuntimeError(
            f"The metric {metric_class.__name__} cannot safely set `full_state_update=False`: "
            f"accumulated compute() diverges: {res1} vs {res2}."
        )

    def _time(m_cls: type) -> float:
        best = float("inf")
        for _ in range(reps):
            m = m_cls(**init_args)
            start = time.perf_counter()
            for _ in range(num_update_to_compare):
                m(**input_args)
            best = min(best, time.perf_counter() - start)
        return best

    t_full = _time(FullState)
    t_partial = _time(PartialState)
    rank_zero_info(
        f"Full state for {metric_class.__name__} metric took: {t_full:.4f}s per {num_update_to_compare} steps\n"
        f"Partial state for {metric_class.__name__} metric took: {t_partial:.4f}s per {num_update_to_compare} steps"
    )
    faster = t_partial < t_full
    rank_zero_info(f"Recommended setting `full_state_update={not faster}`")


def _input_format_classification(preds, target, threshold=0.5, top_k=None, num_classes=None, multiclass=None, ignore_index=None):
    """Reference-named alias of :func:`~torchmetrics_tpu.utilities.formatting.classify_inputs`
    (reference utilities/checks.py:315)."""
    from torchmetrics_tpu.utilities.formatting import classify_inputs

    return classify_inputs(
        preds, target, threshold=threshold, top_k=top_k, num_classes=num_classes,
        multiclass=multiclass, ignore_index=ignore_index,
    )
