"""String-enum task/average dispatch types.

Mirrors the capability of the reference's ``utilities/enums.py``
(/root/reference/src/torchmetrics/utilities/enums.py:56-154): these enums
drive the task-string dispatch (``task="binary"|"multiclass"|"multilabel"``)
and the ``average=`` argument validation.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """Base string enum with forgiving ``from_str`` lookup."""

    @staticmethod
    def _name() -> str:
        return "Task"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "EnumStr":
        try:
            return cls(value.lower().replace("-", "_"))
        except ValueError as err:
            valid = [m.value for m in cls]
            raise ValueError(
                f"Invalid {cls._name()}: expected one of {valid}, but got {value}."
            ) from err

    def __str__(self) -> str:
        return self.value


class DataType(EnumStr):
    """Type of an input tensor as inferred by the input checks."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"

    @classmethod
    def from_str(cls, value: str, source: str = "Key") -> "DataType":  # type: ignore[override]
        try:
            return cls(value.lower())
        except ValueError as err:
            valid = [m.value for m in cls]
            raise ValueError(
                f"Invalid DataType: expected one of {valid}, but got {value}."
            ) from err


class AverageMethod(EnumStr):
    """Reduction over classes: micro/macro/weighted/none/samples."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class averaging."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"


class ClassificationTask(EnumStr):
    """binary | multiclass | multilabel."""

    BINARY = "binary"
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoBinary(EnumStr):
    MULTICLASS = "multiclass"
    MULTILABEL = "multilabel"


class ClassificationTaskNoMultilabel(EnumStr):
    BINARY = "binary"
    MULTICLASS = "multiclass"


def _check_average_arg(average: Optional[str], allowed=("micro", "macro", "weighted", "none", None)) -> None:
    if average not in allowed:
        raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
