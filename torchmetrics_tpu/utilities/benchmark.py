"""Metric micro-benchmark helper (SURVEY §5 row 1: ``metrics.benchmark()``).

The reference's only perf tool is ``check_forward_full_state_property``
(reference utilities/checks.py:636), which wall-clock-times the two eager
forward paths.  On TPU the interesting questions differ: how much device
time does the *jitted* update subgraph cost, how big is the sync'd state,
and how much collective traffic does a mesh sync move.  ``benchmark``
answers all three for any metric instance.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax

from torchmetrics_tpu.core.reductions import Reduce

__all__ = ["benchmark"]


def _state_bytes(state: Dict[str, Any]) -> int:
    total = 0
    for leaf in jax.tree.leaves(state):
        total += int(leaf.size) * leaf.dtype.itemsize
    return total


def benchmark(
    metric: Any,
    *example_inputs: Any,
    steps: int = 100,
    warmup: int = 2,
    n_devices: Optional[int] = None,
    **example_kwargs: Any,
) -> Dict[str, Any]:
    """Measure a metric's jitted update/compute cost and sync footprint.

    Args:
        metric: a metric instance (its state must be jit-compatible —
            tensor states, not list states).
        example_inputs: one representative batch for ``update``.
        steps: timed iterations (chained, so the device queue stays full).
        warmup: untimed compile+warmup calls.
        n_devices: when given, also reports the analytic per-chip reduce
            traffic of one state sync over that many devices.

    Returns a dict with ``update_us``, ``compute_us``, ``state_bytes``,
    ``state_leaves`` and (optionally) ``sync_bytes_per_chip``.
    """
    if getattr(metric, "_has_list_states", False):
        raise ValueError(
            f"{type(metric).__name__} holds list (cat) states, which grow per step and "
            "cannot be timed as a fixed jitted subgraph; benchmark its functional kernel "
            "directly instead."
        )

    update = jax.jit(metric.update_state)
    compute = jax.jit(metric.compute_state)

    state = metric.init_state()
    for _ in range(max(warmup, 1)):
        state = update(state, *example_inputs, **example_kwargs)
    jax.block_until_ready(state)
    result = compute(state)
    jax.block_until_ready(result)

    start = time.perf_counter()
    out = metric.init_state()
    for _ in range(steps):
        out = update(out, *example_inputs, **example_kwargs)
    jax.block_until_ready(out)
    update_us = (time.perf_counter() - start) / steps * 1e6

    start = time.perf_counter()
    for _ in range(steps):
        result = compute(out)
    jax.block_until_ready(result)
    compute_us = (time.perf_counter() - start) / steps * 1e6

    report: Dict[str, Any] = {
        "metric": type(metric).__name__,
        "update_us": round(update_us, 2),
        "compute_us": round(compute_us, 2),
        "state_bytes": _state_bytes(out),
        "state_leaves": len(jax.tree.leaves(out)),
        "device": jax.devices()[0].platform,
    }
    if n_devices is not None and n_devices > 1:
        psum_b = cat_b = 0
        for name, reduce in metric._reductions.items():
            leaf = out[name]
            nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
            if reduce in (Reduce.SUM, Reduce.MEAN, Reduce.MAX, Reduce.MIN):
                psum_b += nbytes  # ring all-reduce: 2(n-1)/n of the buffer per chip
            else:
                cat_b += nbytes  # all_gather: (n-1) x local bytes received per chip
        report["sync_bytes_per_chip"] = int(
            round(2 * (n_devices - 1) / n_devices * psum_b + (n_devices - 1) * cat_b)
        )
    return report
