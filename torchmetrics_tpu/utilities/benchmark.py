"""Metric micro-benchmark helper (SURVEY §5 row 1: ``metrics.benchmark()``).

The reference's only perf tool is ``check_forward_full_state_property``
(reference utilities/checks.py:636), which wall-clock-times the two eager
forward paths.  On TPU the interesting questions differ: how much device
time does the *jitted* update subgraph cost, how big is the sync'd state,
and how much collective traffic does a mesh sync move.  ``benchmark``
answers all three for any metric instance.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from torchmetrics_tpu.core.reductions import Reduce, SketchReduce

__all__ = [
    "RING_GRANULE_BYTES",
    "benchmark",
    "cache_stats_delta",
    "coalesced_sync_bytes_per_chip",
    "collectives_per_sync",
    "gather_wire_bytes_per_chip",
    "per_leaf_sync_bytes_per_chip",
    "reduce_scatter_bytes",
    "ring_reduce_bytes",
    "split_state_bytes",
    "state_bytes",
    "sync_bytes_per_chip",
    "sync_wire_bytes_per_chip",
    "tiled_allgather_bytes",
    "two_stage_dcn_bytes",
    "two_stage_gather_bytes",
]


def cache_stats_delta(after: Dict[str, Any], before: Dict[str, Any]) -> Dict[str, Any]:
    """``after - before`` over two :func:`core.compile.cache_stats` snapshots
    (flat counters and the per-entrypoint breakdown)."""
    out: Dict[str, Any] = {
        k: int(after[k]) - int(before.get(k, 0))
        for k in after
        if isinstance(after[k], int)
    }
    by_after = after.get("by_entrypoint", {})
    by_before = before.get("by_entrypoint", {})
    out["by_entrypoint"] = {
        kind: {
            field: int(n) - int(by_before.get(kind, {}).get(field, 0))
            for field, n in slot.items()
        }
        for kind, slot in by_after.items()
    }
    if "miss_causes" in after:
        mc_before = before.get("miss_causes", {})
        out["miss_causes"] = {
            cause: int(n) - int(mc_before.get(cause, 0))
            for cause, n in after["miss_causes"].items()
        }
    return out


def state_bytes(state: Dict[str, Any]) -> int:
    """Total bytes held by a state pytree."""
    total = 0
    for leaf in jax.tree.leaves(state):
        total += int(leaf.size) * leaf.dtype.itemsize
    return total


def _is_psum_shaped(reduce: Any) -> bool:
    """True when one sync of this leaf rides a ring all-reduce: the
    psum-family reductions plus sketch leaves with an elementwise merge
    (``SketchReduce.bucket_op``); structural sketches and cat/None/callable
    leaves pay the gather model instead."""
    if isinstance(reduce, SketchReduce):
        return reduce.bucket_op is not None
    return reduce in (Reduce.SUM, Reduce.MEAN, Reduce.MAX, Reduce.MIN)


def split_state_bytes(reductions: Dict[str, Any], state: Dict[str, Any]) -> tuple:
    """``(psum_bytes, gather_bytes)`` of a state under its reduction table:
    sum/mean/max/min and bucketed sketch leaves all-reduce; cat/None/
    callable/reservoir leaves all_gather (matching what
    ``core.reductions.sync_leaf`` lowers each to)."""
    psum_b = gather_b = 0
    for name, reduce in reductions.items():
        leaf = state[name]
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
        if _is_psum_shaped(reduce):
            psum_b += nbytes
        else:
            gather_b += nbytes
    return psum_b, gather_b


def sync_bytes_per_chip(reductions: Dict[str, Any], state: Dict[str, Any], n_devices: int) -> int:
    """Analytic per-chip traffic of one state sync over ``n_devices``.

    psum-family states ride a ring all-reduce (``2(n-1)/n`` of the buffer per
    chip); gathered states receive ``(n-1) x`` local bytes per chip.  One
    cost model shared by :func:`benchmark` and ``bench.py``.
    """
    psum_b, gather_b = split_state_bytes(reductions, state)
    return int(round(2 * (n_devices - 1) / n_devices * psum_b + (n_devices - 1) * gather_b))


#: Minimum per-step transfer a ring all-reduce moves on real interconnects:
#: each of the ``2(n-1)`` ring steps sends ``ceil(B/(n*granule))*granule``
#: bytes, so a collective over a tiny buffer still pays one full granule per
#: step.  This is what makes per-leaf syncs of scalar counters so much more
#: expensive than their raw byte count suggests — and what coalescing wins
#: back by amortizing the granule over every fused leaf.
RING_GRANULE_BYTES = 256


def ring_reduce_bytes(
    buffer_bytes: int, n_devices: int, granule: int = RING_GRANULE_BYTES
) -> int:
    """Granule-aware per-chip traffic of ONE ring all-reduce of
    ``buffer_bytes``: ``2(n-1) * ceil(B / (n*granule)) * granule``.

    Reduces to the classic ``2(n-1)/n * B`` as ``B >> n*granule``, but keeps
    the floor a small collective actually pays.
    """
    if n_devices <= 1 or buffer_bytes <= 0:
        return 0
    chunk = math.ceil(buffer_bytes / (n_devices * granule)) * granule
    return int(2 * (n_devices - 1) * chunk)


def reduce_scatter_bytes(
    buffer_bytes: int, n_devices: int, granule: int = RING_GRANULE_BYTES
) -> int:
    """Granule-aware per-chip traffic of ONE ring reduce-scatter of
    ``buffer_bytes``: ``(n-1) * ceil(B / (n*granule)) * granule`` — exactly
    the scatter half of :func:`ring_reduce_bytes`.

    This is what a sharded psum-family state pays per combine once its leaves
    live reduce-scattered instead of replicated (arxiv 2004.13336's weight-
    update sharding applied to metric state); the
    :class:`observability.memory.ShardingAdvisor` quotes the difference as
    the projected wire savings."""
    if n_devices <= 1 or buffer_bytes <= 0:
        return 0
    chunk = math.ceil(buffer_bytes / (n_devices * granule)) * granule
    return int((n_devices - 1) * chunk)


def collectives_per_sync(reductions: Dict[str, Any], state: Dict[str, Any]) -> Dict[str, int]:
    """``{"per_leaf": n, "bucketed": m}`` collective launches for one sync of
    ``state`` — the pre-coalescing one-per-leaf loop vs the planner's fused
    dtype buckets (``parallel.coalesce.build_sync_plan``)."""
    from torchmetrics_tpu.parallel.coalesce import (
        bucketed_collective_count,
        per_leaf_collective_count,
    )

    return {
        "per_leaf": per_leaf_collective_count(reductions, state),
        "bucketed": bucketed_collective_count(reductions, state),
    }


def per_leaf_sync_bytes_per_chip(
    reductions: Dict[str, Any],
    state: Dict[str, Any],
    n_devices: int,
    granule: int = RING_GRANULE_BYTES,
) -> int:
    """Granule-aware per-chip traffic of the pre-coalescing per-leaf sync:
    one ring all-reduce per psum-family leaf (each paying its own granule
    floor) plus ``(n-1)x`` local bytes per gathered leaf."""
    total = 0
    for name, reduce in reductions.items():
        leaf = state[name]
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
        if _is_psum_shaped(reduce) and not isinstance(leaf, tuple):
            total += ring_reduce_bytes(nbytes, n_devices, granule)
        else:
            total += (n_devices - 1) * nbytes
    return int(total)


def coalesced_sync_bytes_per_chip(
    reductions: Dict[str, Any],
    state: Dict[str, Any],
    n_devices: int,
    granule: int = RING_GRANULE_BYTES,
    compression: Any = None,
    shardings: Any = None,
) -> int:
    """Granule-aware per-chip traffic of the coalesced sync: one ring
    all-reduce per planner bucket (the granule floor amortized over every
    fused leaf) plus the per-leaf gather path for passthrough leaves.

    ``compression`` (a ``parallel.compress.CompressionConfig``) prices each
    bucket at its *wire* size — bf16 halves the ring payload, int8's
    two-phase exchange moves the packed ``[int8 | scales]`` blocks — via the
    same per-bucket :func:`parallel.compress.bucket_wire_bytes` model the
    telemetry counters use.  ``None`` reproduces the exact byte model
    bit-for-bit (``bucket_wire_bytes`` with no spec IS the ring formula).

    ``shardings`` (``{leaf: ShardSpec}``) prices sharded SUM buckets at the
    reduce-scatter rate — ``(n-1)`` hops instead of the ring's ``2(n-1)``
    over the divisibility-padded payload — matching the ``psum_scatter``
    lowering those buckets actually trace.
    """
    from torchmetrics_tpu.parallel.coalesce import bucket_scatter_size, build_sync_plan
    from torchmetrics_tpu.parallel.compress import bucket_wire_bytes

    plan = build_sync_plan(
        [(reductions, state)],
        compression=compression,
        shardings=None if not shardings else [shardings],
    )
    total = 0
    for bucket in plan.buckets:
        itemsize = np.dtype(bucket.dtype).itemsize
        total += bucket_wire_bytes(
            bucket_scatter_size(bucket, n_devices),
            itemsize,
            n_devices,
            bucket.compression,
            granule,
            sharded=bucket.sharded,
        )
    for _, name, _ in plan.passthrough:
        leaf = state[name]
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
        total += (n_devices - 1) * nbytes
    return int(total)


def sync_wire_bytes_per_chip(
    reductions: Dict[str, Any],
    state: Dict[str, Any],
    n_devices: int,
    compression: Any = None,
    shardings: Any = None,
) -> int:
    """Granule-free per-chip *wire* traffic of one coalesced sync under an
    optional compression config — the compressed counterpart of
    :func:`sync_bytes_per_chip`, used by telemetry's ``sync_bytes`` counter
    so compressed and raw counters diff cleanly (both granule-free).
    ``shardings`` prices sharded buckets at the reduce-scatter rate."""
    from torchmetrics_tpu.parallel.coalesce import bucket_scatter_size, build_sync_plan
    from torchmetrics_tpu.parallel.compress import bucket_wire_bytes

    plan = build_sync_plan(
        [(reductions, state)],
        compression=compression,
        shardings=None if not shardings else [shardings],
    )
    total = 0
    for bucket in plan.buckets:
        itemsize = np.dtype(bucket.dtype).itemsize
        total += bucket_wire_bytes(
            bucket_scatter_size(bucket, n_devices),
            itemsize,
            n_devices,
            bucket.compression,
            None,
            sharded=bucket.sharded,
        )
    for _, name, _ in plan.passthrough:
        leaf = state[name]
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
        total += (n_devices - 1) * nbytes
    return int(total)


def two_stage_dcn_bytes(
    reductions: Dict[str, Any],
    state: Dict[str, Any],
    n_hosts: int,
    n_local_devices: int,
    granule: int = RING_GRANULE_BYTES,
    compression: Any = None,
) -> Dict[str, int]:
    """Cross-host (DCN) traffic model of one psum-family sync: ``flat``
    reduces over all ``n_hosts * n_local_devices`` participants in one ring
    whose inter-host hops carry every local device's segment, vs
    ``two_stage`` which reduces over ICI inside each host first so ONE
    reduced copy per host crosses DCN — an ``~n_local_devices x`` cut.

    With ``compression``, the payload each host ships over DCN shrinks to
    the host-side packed size (bf16 halves it; int8 ships bytes plus one
    fp32 scale per chunk — ``host_compressed_payload_bytes``), compounding
    with the two-stage cut.
    """
    from torchmetrics_tpu.parallel.coalesce import build_sync_plan
    from torchmetrics_tpu.parallel.compress import host_compressed_payload_bytes

    plan = build_sync_plan([(reductions, state)], compression=compression)
    psum_b = 0
    for b in plan.buckets:
        itemsize = np.dtype(b.dtype).itemsize
        psum_b += host_compressed_payload_bytes(b.size, itemsize, b.compression)
    per_host_ring = ring_reduce_bytes(psum_b, n_hosts, granule)
    return {
        "flat": int(n_local_devices * per_host_ring),
        "two_stage": int(per_host_ring),
    }


def tiled_allgather_bytes(
    buffer_bytes: int, n_devices: int, granule: int = RING_GRANULE_BYTES
) -> int:
    """Granule-aware per-chip traffic of ONE ring all-gather of a
    ``buffer_bytes`` local shard: ``(n-1) * ceil(B / granule) * granule``.

    A ring all-gather forwards each of the ``n-1`` foreign shards once, and
    real interconnects ship each shard in granule-sized tiles — so a tiny
    ragged carry still pays a full tile per hop.  Reduces to the flat
    ``(n-1) * B`` as ``B >> granule``; this is the gather family's
    counterpart of :func:`ring_reduce_bytes` (which models the psum family).
    """
    if n_devices <= 1 or buffer_bytes <= 0:
        return 0
    tile = math.ceil(buffer_bytes / granule) * granule
    return int((n_devices - 1) * tile)


def gather_wire_bytes_per_chip(
    reductions: Dict[str, Any],
    state: Dict[str, Any],
    n_devices: int,
    granule: int = RING_GRANULE_BYTES,
) -> int:
    """Granule-tiled per-chip traffic of the *gather family* of one sync:
    one ring all-gather per cat/None/callable leaf (each paying its own tile
    floor, :func:`tiled_allgather_bytes`); psum-family leaves contribute
    nothing here (they are priced by :func:`sync_bytes_per_chip` /
    :func:`ring_reduce_bytes`)."""
    total = 0
    for name, reduce in reductions.items():
        if _is_psum_shaped(reduce):
            continue
        leaf = state[name]
        nbytes = sum(int(v.size) * v.dtype.itemsize for v in jax.tree.leaves(leaf))
        total += tiled_allgather_bytes(nbytes, n_devices, granule)
    return int(total)


def two_stage_gather_bytes(
    buffer_bytes: int,
    n_hosts: int,
    n_local_devices: int,
    granule: int = RING_GRANULE_BYTES,
) -> Dict[str, int]:
    """Cross-host (DCN) traffic model of one ragged all-gather of a per-chip
    ``buffer_bytes`` cat shard over an ``(n_hosts, n_local_devices)`` mesh:
    ``flat`` gathers over all ``n_hosts * n_local_devices`` participants in
    one ring whose inter-host hops carry every foreign shard — per chip,
    ``(n-1)`` tiles cross DCN — vs ``two_stage`` which all-gathers over ICI
    inside each host first, then exchanges ONE aggregated copy per host over
    DCN, so each chip's amortized DCN share is ``(n_hosts - 1)`` tiles: an
    ``~n_local_devices x`` cut (cross-host bytes scale with hosts, not
    chips — arxiv 2204.06514's topology-aware collective layout applied to
    the gather family).  Unlike the psum family's
    :func:`two_stage_dcn_bytes`, nothing reduces: every byte is distinct, so
    the cut comes purely from moving the fan-out onto ICI.  ``ici`` reports
    the ICI bytes the two-stage route pays per chip (the local gather plus
    redistribution of the foreign hosts' aggregates)."""
    n = int(n_hosts) * int(n_local_devices)
    if n <= 1 or buffer_bytes <= 0:
        return {"flat": 0, "two_stage": 0, "ici": 0}
    tile = math.ceil(buffer_bytes / granule) * granule
    if n_hosts <= 1:  # single host: everything rides ICI
        return {"flat": 0, "two_stage": 0, "ici": int((n - 1) * tile)}
    return {
        "flat": int((n - 1) * tile),
        "two_stage": int((n_hosts - 1) * tile),
        "ici": int(
            (n_local_devices - 1) * tile + (n_hosts - 1) * n_local_devices * tile
        ),
    }


def benchmark(
    metric: Any,
    *example_inputs: Any,
    steps: int = 100,
    warmup: int = 2,
    n_devices: Optional[int] = None,
    **example_kwargs: Any,
) -> Dict[str, Any]:
    """Measure a metric's jitted update/compute cost and sync footprint.

    Args:
        metric: a metric instance (its state must be jit-compatible —
            tensor states, not list states).
        example_inputs: one representative batch for ``update``.
        steps: timed iterations (chained, so the device queue stays full).
        warmup: untimed compile+warmup calls.
        n_devices: when given, also reports the analytic per-chip reduce
            traffic of one state sync over that many devices.

    Returns a dict with ``update_us``, ``compute_us``, ``state_bytes``,
    ``state_leaves``, per-leg compile-cache deltas
    (``cache_stats_delta``: compile/warmup vs update loop vs compute loop —
    a leg's retrace count can no longer be blamed on earlier legs in the
    same process) and (optionally) ``sync_bytes_per_chip``.
    """
    if getattr(metric, "_has_list_states", False):
        raise ValueError(
            f"{type(metric).__name__} holds list (cat) states, which grow per step and "
            "cannot be timed as a fixed jitted subgraph; benchmark its functional kernel "
            "directly instead."
        )

    # route through the unified compile cache: the timed step is the same
    # donated-state callable Metric.update(jit=True) dispatches, so the
    # numbers include in-place accumulator reuse, and repeated benchmark()
    # calls on same-config metrics share one trace
    from torchmetrics_tpu.core.compile import cache_stats, compiled_update

    stats_before = cache_stats()
    update = compiled_update(metric, example_inputs, example_kwargs)
    compute = jax.jit(metric.compute_state)

    state = metric.init_state()
    for _ in range(max(warmup, 1)):
        state = update(state, *example_inputs, **example_kwargs)
    jax.block_until_ready(state)
    result = compute(state)
    jax.block_until_ready(result)
    stats_warm = cache_stats()

    start = time.perf_counter()
    out = metric.init_state()
    for _ in range(steps):
        out = update(out, *example_inputs, **example_kwargs)
    jax.block_until_ready(out)
    update_us = (time.perf_counter() - start) / steps * 1e6
    stats_update = cache_stats()

    start = time.perf_counter()
    for _ in range(steps):
        result = compute(out)
    jax.block_until_ready(result)
    compute_us = (time.perf_counter() - start) / steps * 1e6
    stats_compute = cache_stats()

    report: Dict[str, Any] = {
        "metric": type(metric).__name__,
        "update_us": round(update_us, 2),
        "compute_us": round(compute_us, 2),
        "state_bytes": state_bytes(out),
        "state_leaves": len(jax.tree.leaves(out)),
        "device": jax.devices()[0].platform,
        "donated_state": True,
        "retraces": stats_compute["traces"] - stats_before["traces"],
        # per-leg deltas: retraces (or hits/misses) inside THIS benchmark's
        # sections, uncontaminated by whatever compiled earlier in-process
        "cache_stats_delta": {
            "compile_and_warmup": cache_stats_delta(stats_warm, stats_before),
            "update_loop": cache_stats_delta(stats_update, stats_warm),
            "compute_loop": cache_stats_delta(stats_compute, stats_update),
        },
    }
    if n_devices is not None and n_devices > 1:
        report["sync_bytes_per_chip"] = sync_bytes_per_chip(metric._reductions, out, n_devices)
    return report
