"""Exception types.

TPU-native re-design of the reference's ``utilities/exceptions.py``
(see /root/reference/src/torchmetrics/utilities/exceptions.py:16,20).
"""


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""
