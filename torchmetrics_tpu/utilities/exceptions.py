"""Exception types.

TPU-native re-design of the reference's ``utilities/exceptions.py``
(see /root/reference/src/torchmetrics/utilities/exceptions.py:16,20), plus
the structured resilience errors raised by the checkpoint/restore and
cross-replica verification paths (``torchmetrics_tpu/resilience``).
"""

from typing import Optional, Sequence


class TorchMetricsUserError(Exception):
    """Error raised on wrong usage of the metric API."""


class TorchMetricsUserWarning(UserWarning):
    """Warning raised on questionable usage of the metric API."""


class StateRestoreError(TorchMetricsUserError):
    """A snapshot/state-dict failed validation before being installed.

    Raised by ``resilience.restore`` / ``Metric.load_state_pytree`` /
    ``Metric.load_state_dict`` when a checkpoint's structure, shapes, dtypes,
    or class fingerprint do not match the metric it is being restored into —
    *before* any ``_state`` leaf is touched, so a failed restore never leaves
    a metric half-loaded (and never surfaces as a shape error deep inside a
    compiled update steps later).

    Attributes:
        leaf: name of the offending state leaf (``None`` for structural /
            class-level mismatches).
        reason: machine-readable mismatch category, e.g. ``"shape"``,
            ``"dtype"``, ``"missing-leaf"``, ``"unknown-leaf"``, ``"class"``,
            ``"schema-version"``, ``"mesh-shape"``.
        schema_version: the failing snapshot's recorded schema version, when
            known.
        mesh_shape: the device count (or mesh tuple) the snapshot was
            produced on, when the snapshot recorded it.
        generation: the durable-store generation id the snapshot was loaded
            from, when it came through a :class:`DurableSnapshotStore`.
    """

    def __init__(
        self,
        message: str,
        *,
        leaf: Optional[str] = None,
        reason: Optional[str] = None,
        schema_version: Optional[object] = None,
        mesh_shape: Optional[object] = None,
        generation: Optional[int] = None,
    ) -> None:
        super().__init__(message)
        self.leaf = leaf
        self.reason = reason
        self.schema_version = schema_version
        self.mesh_shape = mesh_shape
        self.generation = generation


class TransientIOError(OSError):
    """A checkpoint I/O failure worth retrying.

    The durable store's :class:`~torchmetrics_tpu.resilience.durable.RetryPolicy`
    classifies failures into *transient* (flaky network filesystem, a stolen
    lease, an interrupted syscall — retry with backoff) and *permanent*
    (``ENOSPC``, a read-only filesystem, a corrupt payload — retrying cannot
    help, surface immediately).  Backends raise this directly for failures
    they know to be transient; plain ``OSError`` subtypes are classified by
    errno (see ``RetryPolicy.is_transient``).
    """


class ReplicaDivergenceError(TorchMetricsUserError):
    """Metric state disagrees across replicas that must hold identical state.

    Raised by ``resilience.verify_replica_consistency`` (and the opt-in
    ``verify_consistency`` hooks in ``parallel.sync.sharded_update`` /
    ``parallel.ragged``) when per-replica state checksums do not agree —
    e.g. after an uneven restore across hosts, or a replica-local
    perturbation.  Catching this at sync time turns a silently wrong
    aggregate into a hard error.

    Attributes:
        leaves: names of the state leaves whose checksums diverged.
        replicas: indices of the replicas that disagree with the majority
            (``None`` when the divergent replica cannot be identified, e.g.
            on the in-graph flag-only path).
    """

    def __init__(
        self,
        message: str,
        *,
        leaves: Sequence[str] = (),
        replicas: Optional[Sequence[int]] = None,
    ) -> None:
        super().__init__(message)
        self.leaves = tuple(leaves)
        self.replicas = tuple(replicas) if replicas is not None else None


class NonFiniteStateError(TorchMetricsUserError):
    """A metric running with ``nan_strategy="error"`` accumulated NaN/Inf.

    The non-finite check is jit-safe: compiled updates only *count*
    non-finite values into a reserved state leaf, and this error is raised by
    the deferred host-side check (``Metric.compute`` / eager ``update``).

    Attributes:
        count: number of non-finite values found in the state.
    """

    def __init__(self, message: str, *, count: int = 0) -> None:
        super().__init__(message)
        self.count = count
