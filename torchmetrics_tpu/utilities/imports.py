"""Dependency-availability flags.

The reference keeps ~40 ``RequirementCache`` flags
(/root/reference/src/torchmetrics/utilities/imports.py:22-63) as its de-facto
feature-flag system.  We reproduce the pattern with a tiny, dependency-free
probe so optional integrations (matplotlib plotting, HF transformers for
BERTScore/CLIP, scipy for Hungarian assignment, ...) degrade gracefully.
"""

from __future__ import annotations

import importlib.util
from functools import lru_cache


@lru_cache(maxsize=None)
def _package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ModuleNotFoundError, ValueError):
        return False


_MATPLOTLIB_AVAILABLE: bool = _package_available("matplotlib")
_SCIPY_AVAILABLE: bool = _package_available("scipy")
_SKLEARN_AVAILABLE: bool = _package_available("sklearn")
_TRANSFORMERS_AVAILABLE: bool = _package_available("transformers")
_FLAX_AVAILABLE: bool = _package_available("flax")
_ORBAX_AVAILABLE: bool = _package_available("orbax")
_EINOPS_AVAILABLE: bool = _package_available("einops")
_TORCH_AVAILABLE: bool = _package_available("torch")
_PANDAS_AVAILABLE: bool = _package_available("pandas")
_PYCOCOTOOLS_AVAILABLE: bool = _package_available("pycocotools")
_REGEX_AVAILABLE: bool = _package_available("regex")
_NLTK_AVAILABLE: bool = _package_available("nltk")


def hf_local_kwargs() -> dict:
    """from_pretrained kwargs enforcing local-only checkpoint resolution.

    Zero-egress default: an unreachable hub id fails fast instead of
    spending ~50s in huggingface-hub's retry loop.  Set
    ``TORCHMETRICS_TPU_ALLOW_DOWNLOAD=1`` to permit network fetches.
    Shared by every HF loader (BERT, CLIP, InfoLM) so the knob cannot
    drift between them.
    """
    import os

    return {} if os.environ.get("TORCHMETRICS_TPU_ALLOW_DOWNLOAD") else {"local_files_only": True}
