"""Shared numeric kernels.

TPU-native counterpart of the reference's ``utilities/compute.py``
(/root/reference/src/torchmetrics/utilities/compute.py:20-162).  All functions
are pure, jittable, static-shape, and avoid data-dependent Python control
flow so they fuse into the surrounding XLA graph.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _safe_matmul(x: Array, y: Array) -> Array:
    """Matmul; kept as a named hook so large cases can be chunked later.

    Reference: utilities/compute.py:20-28 (chunks to avoid CUDA OOM — on TPU
    we let XLA tile onto the MXU instead).
    """
    return x @ y.T


def _safe_xlogy(x: Array, y: Array) -> Array:
    """x * log(y) with 0*log(0) := 0 (reference: compute.py:31-43)."""
    res = jax.scipy.special.xlogy(x, y)
    return res


def _safe_divide(num: Array, denom: Array, zero_division: float = 0.0) -> Array:
    """Elementwise num/denom, returning ``zero_division`` where denom == 0.

    Reference: utilities/compute.py:46-62.
    """
    num = num if jnp.issubdtype(jnp.asarray(num).dtype, jnp.floating) else jnp.asarray(num, jnp.float32)
    denom = denom if jnp.issubdtype(jnp.asarray(denom).dtype, jnp.floating) else jnp.asarray(denom, jnp.float32)
    zero_mask = denom == 0
    safe_denom = jnp.where(zero_mask, 1.0, denom)
    return jnp.where(zero_mask, jnp.asarray(zero_division, dtype=safe_denom.dtype), num / safe_denom)


def _adjust_weights_safe_divide(
    score: Array, average: Optional[str], multilabel: bool, tp: Array, fp: Array, fn: Array,
    top_k: int = 1,
) -> Array:
    """Weighted/macro reduction over per-class scores (reference: compute.py:65-90)."""
    if average is None or average == "none":
        return score
    if average == "weighted":
        weights = tp + fn
    else:
        weights = jnp.ones_like(score)
        if not multilabel:
            # exclude classes absent from both preds and target; with top_k > 1 a
            # class can appear in top-k preds without being a "present" class, so
            # the absence test drops the fp term (reference: utilities/compute.py:73)
            absent = (tp + fp + fn == 0) if top_k == 1 else (tp + fn == 0)
            weights = jnp.where(absent, 0.0, weights)
    return _safe_divide(weights * score, jnp.sum(weights, axis=-1, keepdims=True)).sum(-1)


def _auc_compute(x: Array, y: Array, direction: Optional[float] = None, reorder: bool = False) -> Array:
    """Trapezoidal area under the (x, y) curve.

    Reference: utilities/compute.py:93-136.  The dynamic direction check is
    done with ``jnp.sign`` on the diffs so it stays traceable; ``reorder``
    sorts by x (static-shape argsort).
    """
    if reorder:
        order = jnp.argsort(x, stable=True)
        x, y = x[order], y[order]
    dx = jnp.diff(x)
    if direction is None:
        # all diffs must share a sign; use the sign of the summed diffs
        direction = jnp.where(jnp.all(dx <= 0), -1.0, 1.0)
    return (jnp.trapezoid(y, x) * direction).astype(y.dtype)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Public AUC wrapper (trapezoidal)."""
    return _auc_compute(x, y, reorder=reorder)


def interp(x: Array, xp: Array, fp: Array) -> Array:
    """1-D linear interpolation mirroring ``np.interp``.

    Reference: utilities/compute.py:139-162; jnp has a native vectorized one.
    """
    return jnp.interp(x, xp, fp)


def normalize_logits_if_needed(tensor: Array, normalization: Optional[str]) -> Array:
    """Apply sigmoid/softmax iff values fall outside [0, 1].

    Reference pattern (functional/classification/*_format): ``if not
    ((0 <= preds) & (preds <= 1)).all(): preds = preds.sigmoid()``.  Under
    jit that data-dependent branch must be a ``jnp.where`` — both branches are
    cheap elementwise ops that XLA fuses away.
    """
    if normalization is None:
        return tensor
    outside = jnp.logical_or(jnp.any(tensor < 0), jnp.any(tensor > 1))
    if normalization == "sigmoid":
        return jnp.where(outside, jax.nn.sigmoid(tensor), tensor)
    if normalization == "softmax":
        return jnp.where(outside, jax.nn.softmax(tensor, axis=1), tensor)
    raise ValueError(f"Unknown normalization: {normalization}")
