"""Core data ops on ``jax.Array``.

TPU-native counterpart of the reference's ``utilities/data.py``
(/root/reference/src/torchmetrics/utilities/data.py:28-245).  Notable design
differences from the torch version:

* ``_bincount`` — the reference hand-rolls an arange+eq fallback *specifically
  for XLA* (data.py:203-205).  Here XLA is the native target, so we use a
  scatter-add (``zeros.at[x].add(1)``), which lowers to a single efficient XLA
  scatter and requires a **static** ``minlength`` (always known for
  classification metrics).
* ``dim_zero_cat`` accepts the tuple-of-arrays representation our list states
  use (a tuple of arrays is a valid pytree leaf-set, so states stay jittable).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array


def dim_zero_cat(x: Union[Array, Sequence[Array]]) -> Array:
    """Concatenation along the zero dimension; accepts array, list or tuple of arrays."""
    if isinstance(x, (list, tuple)):
        if len(x) == 0:
            raise ValueError("No samples to concatenate")
        x = [jnp.atleast_1d(xi) for xi in x]
        return jnp.concatenate(x, axis=0)
    return x


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten list of lists into a single list."""
    return [item for sublist in x for item in sublist]


def _flatten_dict(x: Dict) -> Tuple[Dict, bool]:
    """Flatten dict of dicts into a single dict; returns (flat, all_unique)."""
    new_dict = {}
    duplicates = False
    for key, value in x.items():
        if isinstance(value, dict):
            for k, v in value.items():
                if k in new_dict:
                    duplicates = True
                new_dict[k] = v
        else:
            if key in new_dict:
                duplicates = True
            new_dict[key] = value
    return new_dict, not duplicates


def to_onehot(label_tensor: Array, num_classes: int) -> Array:
    """Convert a dense label tensor ``(N, ...)`` to one-hot ``(N, C, ...)``.

    Reference: utilities/data.py:80-122.
    """
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32, axis=1)
    return onehot


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Inverse of :func:`to_onehot` via argmax along ``argmax_dim``."""
    return jnp.argmax(x, axis=argmax_dim)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binary mask of the top-k largest entries along ``dim``.

    Reference: utilities/data.py:125-176.  Implemented with
    ``jax.lax.top_k`` (static k) + scatter — both MXU/XLA friendly.
    """
    if topk == 1:  # fast path: pure argmax one-hot
        idx = jnp.argmax(prob_tensor, axis=dim)
        return jax.nn.one_hot(idx, prob_tensor.shape[dim], dtype=jnp.int32, axis=dim)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    onehots = jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32).sum(axis=-2)
    return jnp.moveaxis(onehots, -1, dim)


def _bincount(x: Array, minlength: int) -> Array:
    """Count occurrences of each value in 0..minlength-1.

    Static-length scatter-add — single XLA scatter op, deterministic, and
    (unlike ``torch.bincount``) well-defined under jit.  ``minlength`` must be
    static.  Reference context: utilities/data.py:179-207.
    """
    return jnp.bincount(x.reshape(-1), length=minlength)


def _cumsum(x: Array, axis: int = 0) -> Array:
    """Cumulative sum — XLA's is already deterministic on TPU.

    (Reference works around nondeterministic CUDA cumsum at data.py:210-219;
    no workaround is needed here.)
    """
    return jnp.cumsum(x, axis=axis)


def allclose(t1: Array, t2: Array, atol: float = 1e-8) -> bool:
    """dtype-robust allclose (reference: utilities/data.py:241-245)."""
    if t1.shape != t2.shape:
        return False
    return bool(jnp.allclose(t1.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32),  # tmt: ignore[TMT008] -- x64 branch explicitly gated on jax_enable_x64; float32 otherwise
                             t2.astype(jnp.float64 if jax.config.jax_enable_x64 else jnp.float32),  # tmt: ignore[TMT008] -- x64 branch explicitly gated on jax_enable_x64; float32 otherwise
                             atol=atol))
