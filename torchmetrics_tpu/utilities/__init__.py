from torchmetrics_tpu.utilities.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from torchmetrics_tpu.utilities.benchmark import benchmark
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utilities.formatting import classify_inputs
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_info, rank_zero_warn
from torchmetrics_tpu.utilities.regression import (
    RegressionTracker,
    check_regressions,
    load_bench_history,
)

__all__ = [
    "benchmark",
    "check_regressions",
    "load_bench_history",
    "RegressionTracker",
    "classify_inputs",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
]
