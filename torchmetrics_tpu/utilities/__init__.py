from torchmetrics_tpu.utilities.data import (
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from torchmetrics_tpu.utilities.benchmark import benchmark
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utilities.formatting import classify_inputs
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_info, rank_zero_warn

__all__ = [
    "benchmark",
    "classify_inputs",
    "dim_zero_cat",
    "dim_zero_max",
    "dim_zero_mean",
    "dim_zero_min",
    "dim_zero_sum",
    "rank_zero_debug",
    "rank_zero_info",
    "rank_zero_warn",
    "TorchMetricsUserError",
    "TorchMetricsUserWarning",
]
