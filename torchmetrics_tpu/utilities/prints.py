"""Rank-zero logging helpers.

Equivalent of the reference's ``utilities/prints.py``
(/root/reference/src/torchmetrics/utilities/prints.py:22-73), re-keyed on
``jax.process_index()`` instead of the ``LOCAL_RANK`` env var: in a JAX
multi-host program the process index is the rank.
"""

from __future__ import annotations

import logging
import warnings
from functools import partial, wraps
from typing import Any, Callable

log = logging.getLogger("torchmetrics_tpu")
# Library logging etiquette: a NullHandler on the package root means an
# application that never configures logging sees neither "No handlers could
# be found" noise nor unformatted last-resort output, while an application
# that does configure the root (or this) logger gets every record exactly
# once through its own handlers.  Child loggers — e.g. the observability
# exporters' "torchmetrics_tpu.observability" — propagate up through here.
if not any(isinstance(h, logging.NullHandler) for h in log.handlers):
    log.addHandler(logging.NullHandler())


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax uninitialized
        return 0


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0 of a multi-host program."""

    @wraps(fn)
    def wrapped_fn(*args: Any, **kwargs: Any) -> Any:
        if _rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped_fn


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, **kwargs: Any) -> None:
    kwargs.setdefault("stacklevel", 5)
    warnings.warn(message, *args, **kwargs)


@rank_zero_only
def rank_zero_info(message: str, *args: Any, **kwargs: Any) -> None:
    log.info(message, *args, **kwargs)


@rank_zero_only
def rank_zero_debug(message: str, *args: Any, **kwargs: Any) -> None:
    log.debug(message, *args, **kwargs)


def _deprecated_root_import_class(name: str, domain: str) -> None:
    rank_zero_warn(
        f"`torchmetrics_tpu.{name}` was deprecated and will be removed. "
        f"Import `torchmetrics_tpu.{domain}.{name}` instead.",
        DeprecationWarning,
    )


def _deprecated_root_import_func(name: str, domain: str) -> None:
    rank_zero_warn(
        f"`torchmetrics_tpu.functional.{name}` was deprecated and will be removed. "
        f"Import `torchmetrics_tpu.functional.{domain}.{name}` instead.",
        DeprecationWarning,
    )
