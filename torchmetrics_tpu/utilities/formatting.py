"""Flexible classification input canonicalization.

The shared input-format layer the reference builds in
``utilities/checks.py`` (``_check_classification_inputs`` :207,
``_input_format_classification`` :315): heterogeneous classification inputs
— float probabilities/logits or integer labels, with or without a class
dimension, with extra spatial dims — are auto-classified into one of four
cases and canonicalized to binary ``(N, C)`` / ``(N, C, X)`` tensors that
every downstream kernel can consume uniformly.

The decision table is behaviorally identical to the reference's (property-
tested against it case-by-case in
tests/unittests/utilities/test_formatting.py); the structure here is a
detect → validate → canonicalize pipeline over one rules table rather than
the reference's chain of per-aspect check functions.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.utilities.data import select_topk, to_onehot
from torchmetrics_tpu.utilities.enums import DataType

__all__ = ["classify_inputs", "DataType"]


def _is_float(x: np.ndarray) -> bool:
    # np.issubdtype is False for ml_dtypes.bfloat16 — the dtype TPU
    # probabilities most commonly arrive in — so check it by name
    return np.issubdtype(x.dtype, np.floating) or x.dtype.name == "bfloat16"


def _squeeze_excess(preds: np.ndarray, target: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Drop every size-1 dimension except the leading batch dim."""
    if preds.shape[:1] == (1,):
        return preds.squeeze()[None], target.squeeze()[None]
    return preds.squeeze(), target.squeeze()


def _detect_case(preds: np.ndarray, target: np.ndarray) -> Tuple[DataType, int]:
    """Classify the (preds, target) shape/dtype combination.

    Returns the case and the implied class count (``C`` dim for multi-class
    probabilities, flattened extra dims for multi-label).
    """
    floating = _is_float(preds)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                f"`preds` and `target` with equal rank must have equal shape; got "
                f"{preds.shape} vs {target.shape}."
            )
        if floating and target.size and target.max() > 1:
            raise ValueError(
                "With same-shaped float `preds`, `target` must be binary (0/1)."
            )
        if preds.ndim == 1:
            case = DataType.BINARY if floating else DataType.MULTICLASS
        else:
            case = DataType.MULTILABEL if floating else DataType.MULTIDIM_MULTICLASS
        implied = int(preds[0].size) if preds.size else 0
        return case, implied

    if preds.ndim == target.ndim + 1:
        if not floating:
            raise ValueError(
                "`preds` with one extra dimension must be float probabilities/logits."
            )
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "With an extra class dimension, `preds` must be (N, C, ...) and "
                "`target` (N, ...) over the same trailing dims."
            )
        implied = int(preds.shape[1]) if preds.size else 0
        return (DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS), implied

    raise ValueError(
        "Shapes must be either identical (N, ...) for both, or (N, C, ...) `preds` "
        f"with (N, ...) `target`; got {preds.shape} and {target.shape}."
    )


def _validate(
    preds: np.ndarray,
    target: np.ndarray,
    case: DataType,
    implied: int,
    top_k: Optional[int],
    num_classes: Optional[int],
    multiclass: Optional[bool],
    ignore_index: Optional[int],
) -> None:
    """The reference's consistency rules, one place (checks.py:96-205,271-302)."""
    floating = _is_float(preds)

    # mirrors the reference's exact condition (checks.py:62), including its
    # falsy-zero quirk: ignore_index=0 disables the negativity check
    if target.size and target.min() < 0 and (
        ignore_index is None or (ignore_index and ignore_index >= 0)
    ):
        raise ValueError("`target` must be non-negative.")
    if not floating and preds.size and preds.min() < 0:
        raise ValueError("Integer `preds` must be non-negative.")
    if multiclass is False:
        if target.size and target.max() > 1:
            raise ValueError("`multiclass=False` requires `target` values <= 1.")
        if not floating and preds.size and preds.max() > 1:
            raise ValueError("`multiclass=False` requires integer `preds` values <= 1.")

    if preds.shape != target.shape:  # C-dim cases
        if multiclass is False and implied != 2:
            raise ValueError(
                "`multiclass=False` needs exactly 2 classes along the C dimension of `preds`."
            )
        if target.size and target.max() >= implied:
            raise ValueError(
                "The highest `target` label must be below the C dimension of `preds`."
            )

    if num_classes:
        if case == DataType.BINARY:
            if num_classes > 2:
                raise ValueError("Binary data cannot have `num_classes` > 2.")
            if num_classes == 2 and not multiclass:
                raise ValueError(
                    "Binary data with `num_classes=2` needs `multiclass=True` to be "
                    "promoted to multi-class format."
                )
            if num_classes == 1 and multiclass:
                raise ValueError(
                    "Binary data with `multiclass=True` needs `num_classes=2` (or unset)."
                )
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            if num_classes == 1 and multiclass is not False:
                raise ValueError(
                    "`num_classes=1` on multi-class data requires `multiclass=False` "
                    "(demote two-class data to binary/multi-label)."
                )
            if num_classes > 1:
                if multiclass is False and implied != num_classes:
                    raise ValueError(
                        "`multiclass=False` demotion requires `num_classes` to match the "
                        "implied class count."
                    )
                if target.size and num_classes <= target.max():
                    raise ValueError("The highest `target` label must be below `num_classes`.")
                if not floating and preds.size and num_classes <= preds.max():
                    # the reference rejects this via its scatter one-hot;
                    # jax.nn.one_hot would silently emit a zero row instead
                    raise ValueError("The highest `preds` label must be below `num_classes`.")
                if preds.shape != target.shape and num_classes != implied:
                    raise ValueError("`num_classes` must match the C dimension of `preds`.")
        else:  # multi-label
            if multiclass and num_classes != 2:
                raise ValueError(
                    "Promoting multi-label data with `multiclass=True` requires "
                    "`num_classes` of 2 or None."
                )
            if not multiclass and num_classes != implied:
                raise ValueError("`num_classes` must match the implied label count.")

    if top_k is not None:
        if case == DataType.BINARY:
            raise ValueError("`top_k` does not apply to binary data.")
        if not isinstance(top_k, int) or top_k <= 0:
            raise ValueError("`top_k` must be a positive integer.")
        if not floating:
            raise ValueError("`top_k` needs probability `preds`, not labels.")
        if multiclass is False:
            raise ValueError("`top_k` cannot combine with `multiclass=False`.")
        if case == DataType.MULTILABEL and multiclass:
            raise ValueError("`top_k` cannot combine with multi-label promotion.")
        if top_k >= implied:
            raise ValueError("`top_k` must be strictly below the C dimension of `preds`.")


def classify_inputs(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, DataType]:
    """Auto-classify and canonicalize flexible classification inputs.

    Accepted shapes (mirroring the reference's table, checks.py:315-380):

    ========================  =================  =======================
    preds                     target             case
    ========================  =================  =======================
    float (N,)                binary int (N,)    binary
    int (N,)                  int (N,)           multi-class
    float (N, C)              int (N,)           multi-class
    float (N, ...)            binary int (N,...) multi-label
    float (N, C, ...)         int (N, ...)       multi-dim multi-class
    int (N, ...)              int (N, ...)       multi-dim multi-class
    ========================  =================  =======================

    Returns int binary tensors of shape ``(N, C)`` or ``(N, C, X)`` plus the
    detected :class:`DataType`.  ``multiclass`` promotes/demotes between the
    binary and two-class representations exactly as the reference does.
    Consumed by the legacy-style entry points (e.g.
    :class:`~torchmetrics_tpu.classification.Dice`) and public for building
    layout-agnostic metrics.

    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.utilities import classify_inputs
        >>> # binary probabilities -> thresholded (N, 1) masks
        >>> p, t, case = classify_inputs(jnp.asarray([0.2, 0.8]), jnp.asarray([0, 1]))
        >>> (case.value, p.ravel().tolist())
        ('binary', [0, 1])
        >>> # (N, C) probabilities + labels -> one-hot top-1
        >>> p, t, case = classify_inputs(
        ...     jnp.asarray([[0.1, 0.9], [0.7, 0.3]]), jnp.asarray([1, 0]))
        >>> (case.value, p.tolist(), t.tolist())
        ('multi-class', [[0, 1], [1, 0]], [[0, 1], [1, 0]])
    """
    p = np.asarray(preds)
    t = np.asarray(target)

    if not (p.size == 0 and t.size == 0):
        if np.issubdtype(t.dtype, np.floating):
            raise ValueError("`target` must be an integer tensor.")
        if p.shape[:1] != t.shape[:1]:
            raise ValueError("`preds` and `target` must agree on the batch dimension.")

    p, t = _squeeze_excess(p, t)
    if p.dtype == np.float16 or p.dtype.name == "bfloat16":
        p = p.astype(np.float32)

    case, implied = _detect_case(p, t)
    if not (p.size == 0 and t.size == 0):
        _validate(p, t, case, implied, top_k, num_classes, multiclass, ignore_index)

    pj = jnp.asarray(p)
    tj = jnp.asarray(t)
    preds_are_probs = _is_float(p)

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        pj = (pj >= threshold).astype(jnp.int32)
        preds_are_probs = False
        num_classes = 2 if multiclass else num_classes
    if case == DataType.MULTILABEL and top_k:
        pj = select_topk(pj, top_k)
        preds_are_probs = False

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if preds_are_probs:
            num_classes = p.shape[1]
            pj = select_topk(pj, top_k or 1)
        else:
            if not num_classes:
                num_classes = int(max(p.max(initial=0), t.max(initial=0)) + 1) if p.size else 1
            pj = to_onehot(pj, max(2, num_classes))
        tj = to_onehot(tj, max(2, num_classes))
        if multiclass is False:
            pj, tj = pj[:, 1, ...], tj[:, 1, ...]

    if pj.size or tj.size:
        promote = (
            case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False
        ) or multiclass
        if promote:
            pj = pj.reshape(pj.shape[0], pj.shape[1], -1)
            tj = tj.reshape(tj.shape[0], tj.shape[1], -1)
        else:
            pj = pj.reshape(pj.shape[0], -1)
            tj = tj.reshape(tj.shape[0], -1)

    if pj.ndim > 2 and pj.shape[-1] == 1:
        pj, tj = pj.squeeze(-1), tj.squeeze(-1)

    return pj.astype(jnp.int32), tj.astype(jnp.int32), case
