"""Plotting helpers (matplotlib optional).

Counterpart of the reference's ``utilities/plot.py``
(/root/reference/src/torchmetrics/utilities/plot.py:64,220,296).
"""

from __future__ import annotations

from itertools import product
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchmetrics_tpu.utilities.imports import _MATPLOTLIB_AVAILABLE

if _MATPLOTLIB_AVAILABLE:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
else:  # pragma: no cover
    plt = None


def _error_on_missing_matplotlib() -> None:
    if not _MATPLOTLIB_AVAILABLE:
        raise ModuleNotFoundError(
            "Plot function expects `matplotlib` to be installed. Install with `pip install matplotlib`"
        )


def plot_single_or_multi_val(
    val: Any,
    ax: Optional[Any] = None,
    higher_is_better: Optional[bool] = None,
    lower_bound: Optional[float] = None,
    upper_bound: Optional[float] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot a single scalar result, a per-class vector, a dict, or a sequence over time.

    Reference: utilities/plot.py:64-217.
    """
    _error_on_missing_matplotlib()
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()

    def _as_np(v):
        return np.asarray(v)

    if isinstance(val, dict):
        for i, (k, v) in enumerate(val.items()):
            arr = _as_np(v)
            if arr.ndim == 0:
                ax.plot([i], [float(arr)], "o", label=k)
            else:
                ax.plot(arr, label=k)
        ax.legend()
    elif isinstance(val, (list, tuple)) and len(val) > 0 and not np.isscalar(val[0]):
        arrs = [_as_np(v) for v in val]
        stacked = np.stack([a.reshape(-1) for a in arrs])
        for c in range(stacked.shape[1]):
            label = f"{legend_name or 'class'}_{c}" if stacked.shape[1] > 1 else (name or "value")
            ax.plot(np.arange(len(arrs)), stacked[:, c], "-o", label=label)
        ax.legend()
        ax.set_xlabel("step")
    else:
        arr = _as_np(val)
        if arr.ndim == 0:
            ax.plot([0], [float(arr)], "o", label=name or "value")
        else:
            for c, v in enumerate(arr.reshape(-1)):
                ax.plot([c], [float(v)], "o", label=f"{legend_name or 'class'}_{c}")
        ax.legend()

    if lower_bound is not None and upper_bound is not None:
        ax.set_ylim(lower_bound, upper_bound)
    if name is not None:
        ax.set_title(name)
    ax.grid(True, alpha=0.3)
    return fig, ax


def plot_confusion_matrix(
    confmat: Any,
    ax: Optional[Any] = None,
    add_text: bool = True,
    labels: Optional[List[str]] = None,
    cmap: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Heatmap plot of a (C, C) or (N, C, C) confusion matrix.

    Reference: utilities/plot.py:220-293.
    """
    _error_on_missing_matplotlib()
    confmat = np.asarray(confmat)
    if confmat.ndim == 3:  # multilabel
        nb, n_classes = confmat.shape[0], 2
        rows, cols = 1, nb
    else:
        nb, n_classes = 1, confmat.shape[0]
        rows = cols = 1
        confmat = confmat[None]

    if labels is None:
        labels = list(map(str, range(n_classes)))

    fig, axs = (ax.get_figure(), [ax]) if ax is not None else plt.subplots(rows, cols, squeeze=False)
    axs = np.asarray(axs).reshape(-1)
    for i in range(nb):
        a = axs[i] if i < len(axs) else axs[0]
        a.imshow(confmat[i], cmap=cmap or "viridis")
        a.set_xlabel("Predicted class")
        a.set_ylabel("True class")
        a.set_xticks(range(n_classes))
        a.set_yticks(range(n_classes))
        a.set_xticklabels(labels)
        a.set_yticklabels(labels)
        if add_text:
            for ii, jj in product(range(n_classes), range(n_classes)):
                a.text(jj, ii, str(round(float(confmat[i, ii, jj]), 2)), ha="center", va="center")
    return fig, axs[0] if nb == 1 else axs


def plot_curve(
    curve: Tuple[Any, Any, Any],
    score: Optional[Any] = None,
    ax: Optional[Any] = None,
    label_names: Optional[Tuple[str, str]] = None,
    legend_name: Optional[str] = None,
    name: Optional[str] = None,
) -> Tuple[Any, Any]:
    """Plot a (x, y, thresholds) curve family — ROC / PR curves.

    Reference: utilities/plot.py:296-365.
    """
    _error_on_missing_matplotlib()
    x, y = np.asarray(curve[0]), np.asarray(curve[1])
    fig, ax = (ax.get_figure(), ax) if ax is not None else plt.subplots()
    if x.ndim == 1:
        label = name or "curve"
        if score is not None:
            label += f" (score={float(np.asarray(score)):.3f})"
        ax.plot(x, y, linestyle="-", linewidth=2, label=label)
    else:
        for c in range(x.shape[0]):
            label = f"{legend_name or 'class'}_{c}"
            ax.plot(x[c], y[c], linestyle="-", linewidth=2, label=label)
    ax.grid(True, alpha=0.3)
    ax.legend()
    if label_names is not None:
        ax.set_xlabel(label_names[0])
        ax.set_ylabel(label_names[1])
    if name is not None:
        ax.set_title(name)
    return fig, ax
