"""Bench regression tracker: gate the current bench run against history.

``bench.py`` emits one JSON record per run and the driver archives them as
``BENCH_r<NN>.json`` (``{"n": run-number, "cmd": ..., "rc": exit-code,
"tail": last-stdout-bytes, "parsed": last-JSON-line-or-null}``).  Until now
those were write-only: a perf regression landed silently and was only
noticed by a human reading the next archive.  This module closes the loop:

* :func:`load_bench_history` parses every archived run — including the
  degraded shapes real archives have (``rc != 0`` crash records, ``parsed:
  null`` with a *truncated* ``tail`` whose JSON can only be partially
  recovered) — into flat ``{dotted.key: value}`` series;
* :class:`RegressionTracker` compares the current run per leg against the
  most recent comparable baseline (same device class — a CPU-fallback run
  must never be judged against TPU numbers) inside direction-aware noise
  bands: wall-clock legs get a wide band, analytic/deterministic legs
  (byte models, collective counts, retrace counters) a tight one;
* :class:`RegressionReport` renders a pass/fail markdown table and a
  machine-readable verdict dict — wired into ``bench.py
  --check-regressions``.

The tracker is import-light (stdlib only) so it can run in CI without JAX.
"""

from __future__ import annotations

import json
import math
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "BenchRun",
    "LegComparison",
    "RegressionReport",
    "RegressionTracker",
    "check_regressions",
    "flatten_numeric",
    "load_bench_history",
    "recover_numeric_pairs",
]

DEFAULT_PATTERN = "BENCH_r[0-9]*.json"

#: Relative noise bands by key class.  Wall-clock legs vary wildly across
#: container generations; analytic legs (byte models, planner counts,
#: retrace counters) are deterministic and get a tight band.
TIMING_BAND = 0.60
ANALYTIC_BAND = 0.01
DEFAULT_BAND = 0.30

_ANALYTIC_MARKERS = (
    "_bytes",
    "_collectives",
    "retraces",
    "_traces",
    "_misses",
    "state_leaves",
    "n_pairs",
)
#: keys where a LOWER value is better (gate on increases)
_LOWER_BETTER = (
    "_us",
    "_ms",
    "wall_s",
    "_bytes",
    "_waste_bytes",  # ShardingAdvisor replicated-HBM waste (subsumed by _bytes;
    "_hbm_bytes",  # listed with _hbm_bytes so the gate survives a _bytes edit)
    "overhead",
    "retraces",
    "_misses",
    "_collectives",
    "findings",
    "_err",  # sketch-vs-exact error legs (abs err, error bounds)
    "_bound",  # attested error bounds (accuracy plane): a growing bound is a regression
    "skew",  # fleet skew ratios: growing imbalance is a regression
    "alerts",  # health-monitor alert counts on the deterministic bench stream
    "_sync_s",  # autotune-leg sync wall times (naive/hand-tuned/autotuned)
    "_ckpt_s",  # durable checkpoint save/restore wall times (commit protocol + verified read)
    "_start_s",  # warm-start leg time-to-first-step (cold_start_s / warm_start_s)
    "_gather_bytes",  # gather-leg modelled/projected cat-state traffic (subsumed by
    "_gather_s",  # _bytes; listed with _gather_s so the gate survives a _bytes edit)
)
#: keys where a HIGHER value is better (gate on decreases)
_HIGHER_BETTER = ("cut", "speedup", "drop_pct", "fused_to", "prometheus_lines")


def flatten_numeric(
    obj: Any, prefix: str = "", max_depth: int = 8
) -> Dict[str, float]:
    """Flatten the numeric leaves of a nested bench record into
    ``{"dotted.key": value}`` (bools excluded — they are verdicts, not
    series)."""
    out: Dict[str, float] = {}
    if max_depth < 0:
        return out
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten_numeric(v, key, max_depth - 1))
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            key = f"{prefix}.{i}" if prefix else str(i)
            out.update(flatten_numeric(v, key, max_depth - 1))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)) and math.isfinite(obj):
        out[prefix] = float(obj)
    return out


_NUM_PAIR = re.compile(r'"([A-Za-z_][A-Za-z0-9_]*)":\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)')
_DEVICE = re.compile(r'"device":\s*"([A-Za-z0-9_-]+)"')


def recover_numeric_pairs(text: str) -> Dict[str, float]:
    """Best-effort scalar recovery from a *truncated* JSON tail (the archive
    keeps only the last N bytes of stdout, so the record can start
    mid-object).  Returns every unambiguous ``"key": number`` pair; keys that
    appear more than once with different values are dropped — with the
    nesting gone there is no way to tell whose value is whose."""
    seen: Dict[str, float] = {}
    ambiguous = set()
    for key, num in _NUM_PAIR.findall(text):
        val = float(num)
        if key in seen and seen[key] != val:
            ambiguous.add(key)
        seen[key] = val
    return {k: v for k, v in seen.items() if k not in ambiguous}


@dataclass
class BenchRun:
    """One archived bench run, reduced to flat numeric series."""

    n: int
    rc: int
    source: str
    device: Optional[str] = None
    values: Dict[str, float] = field(default_factory=dict)
    partial: bool = False  # recovered from a truncated tail

    def lookup(self, dotted_key: str) -> Optional[float]:
        """Value for ``dotted_key``: exact match, else a unique dotted-suffix
        match (partial recoveries lose the nesting, keeping only leaf
        names)."""
        if dotted_key in self.values:
            return self.values[dotted_key]
        leaf = dotted_key.rsplit(".", 1)[-1]
        if leaf in self.values:
            return self.values[leaf]
        hits = [v for k, v in self.values.items() if k.endswith("." + leaf)]
        return hits[0] if len(hits) == 1 else None


def _parse_archive(path: Path) -> Optional[BenchRun]:
    try:
        raw = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(raw, Mapping):
        return None
    n = int(raw.get("n", 0))
    rc = int(raw.get("rc", 1))
    parsed = raw.get("parsed")
    tail = str(raw.get("tail") or "")
    if isinstance(parsed, Mapping):
        values = flatten_numeric(parsed)
        device = _DEVICE.search(json.dumps(parsed))
        return BenchRun(
            n=n, rc=rc, source=path.name,
            device=device.group(1) if device else None, values=values,
        )
    # degraded archive: try whole JSON lines in the tail first, then the
    # truncated-object scalar recovery
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, Mapping) and "metric" in obj:
                device = _DEVICE.search(line)
                return BenchRun(
                    n=n, rc=rc, source=path.name,
                    device=device.group(1) if device else None,
                    values=flatten_numeric(obj),
                )
    values = recover_numeric_pairs(tail)
    if not values:
        return None
    device = _DEVICE.search(tail)
    return BenchRun(
        n=n, rc=rc, source=path.name,
        device=device.group(1) if device else None,
        values=values, partial=True,
    )


def load_bench_history(
    directory: str = ".", pattern: str = DEFAULT_PATTERN
) -> List[BenchRun]:
    """Every parseable ``BENCH_r*.json`` in ``directory``, oldest first.
    Crash records (``rc != 0``) and unrecoverable tails are skipped — a run
    that produced no numbers can neither be a baseline nor regress."""
    runs: List[BenchRun] = []
    for path in sorted(Path(directory).glob(pattern)):
        run = _parse_archive(path)
        if run is not None and run.rc == 0 and run.values:
            runs.append(run)
    runs.sort(key=lambda r: r.n)
    return runs


def direction_for(key: str) -> Optional[str]:
    """``"lower"`` / ``"higher"`` = which way is better; ``None`` = the key
    is descriptive (shapes, configs) and is reported but never gated."""
    leaf = key.rsplit(".", 1)[-1]
    for marker in _HIGHER_BETTER:
        if marker in leaf:
            return "higher"
    # markers match against "_" + leaf so prefix leaves gate too: a marker
    # "_bytes" catches "sync_bytes" AND bare "bytes" / "bytes_per_chip"
    for marker in _LOWER_BETTER:
        if marker in f"_{leaf}" or leaf.endswith(("_s", "_us", "_ms")):
            return "lower"
    return None


_TIMING_TOKENS = frozenset({"us", "ms", "s", "wall", "time"})


def band_for(key: str, noise_band: float = DEFAULT_BAND) -> float:
    leaf = key.rsplit(".", 1)[-1]
    if _TIMING_TOKENS & set(leaf.split("_")):
        return max(TIMING_BAND, noise_band)
    if any(m in f"_{leaf}" for m in _ANALYTIC_MARKERS):
        return ANALYTIC_BAND
    return noise_band


def _denom_for(key: str, baseline: float) -> float:
    """Scale for relative deltas/bands.  Percentage legs get a one-point
    floor: their baselines hover near (or below) zero, where a raw relative
    band degenerates — a sub-point move on an overhead-% leg is noise."""
    denom = abs(baseline)
    if "pct" in key.rsplit(".", 1)[-1]:
        denom = max(denom, 1.0)
    return denom or 1.0


@dataclass
class LegComparison:
    key: str
    current: float
    baseline: float
    baseline_run: str
    delta_pct: float  # signed, relative to baseline (0 baseline -> inf-safe)
    band_pct: float
    direction: Optional[str]
    verdict: str  # "pass" | "fail" | "info"

    def as_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class RegressionReport:
    verdict: str  # "pass" | "fail" | "no-baseline"
    comparisons: List[LegComparison]
    baseline_runs: List[str]
    device: Optional[str]
    skipped_device_mismatch: int = 0

    @property
    def failures(self) -> List[LegComparison]:
        return [c for c in self.comparisons if c.verdict == "fail"]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "metric": "bench-regression-check",
            "verdict": self.verdict,
            "device": self.device,
            "baseline_runs": self.baseline_runs,
            "n_compared": len(self.comparisons),
            "n_gated": sum(1 for c in self.comparisons if c.direction is not None),
            "n_failures": len(self.failures),
            "skipped_device_mismatch": self.skipped_device_mismatch,
            "failures": [c.as_dict() for c in self.failures],
        }

    def to_markdown(self) -> str:
        lines = [
            "## Bench regression check",
            "",
            f"**Verdict: {self.verdict.upper()}** — "
            f"{len(self.comparisons)} legs compared against "
            f"{', '.join(self.baseline_runs) or '(no baseline)'}"
            + (f" on `{self.device}`" if self.device else "")
            + f"; {len(self.failures)} failure(s), "
            f"{self.skipped_device_mismatch} leg(s) skipped (device mismatch).",
            "",
        ]
        gated = [c for c in self.comparisons if c.direction is not None]
        if gated:
            lines += [
                "| leg | current | baseline | Δ% | band | better | verdict |",
                "|---|---:|---:|---:|---:|:-:|:-:|",
            ]
            order = {"fail": 0, "pass": 1}
            for c in sorted(gated, key=lambda c: (order.get(c.verdict, 2), c.key)):
                mark = "❌" if c.verdict == "fail" else "✅"
                lines.append(
                    f"| `{c.key}` | {c.current:g} | {c.baseline:g} "
                    f"({c.baseline_run}) | {c.delta_pct:+.1f}% | "
                    f"±{c.band_pct * 100:.0f}% | {c.direction} | {mark} {c.verdict} |"
                )
        info = [c for c in self.comparisons if c.direction is None]
        if info:
            lines += ["", f"_{len(info)} ungated (descriptive) legs tracked but not gated._"]
        return "\n".join(lines) + "\n"


class RegressionTracker:
    """Compare a current bench record against archived ``BENCH_r*.json``
    history with per-leg noise bands.

    ``noise_band`` is the default relative band; wall-clock legs widen to
    ``TIMING_BAND`` and analytic legs tighten to ``ANALYTIC_BAND`` (see
    :func:`band_for`).  Baselines come from the most recent clean run whose
    device matches the current run's — when none matches, the check reports
    ``no-baseline`` rather than failing on apples-vs-oranges numbers.
    """

    def __init__(
        self,
        history_dir: str = ".",
        pattern: str = DEFAULT_PATTERN,
        noise_band: float = DEFAULT_BAND,
        history: Optional[Sequence[BenchRun]] = None,
    ) -> None:
        self.noise_band = float(noise_band)
        self.history: List[BenchRun] = (
            list(history) if history is not None else load_bench_history(history_dir, pattern)
        )

    #: historical spread is inflated by this factor when deriving the
    #: empirical band — one prior excursion should not sit exactly on the line
    HISTORY_SPREAD_FACTOR = 1.5

    def _baseline_for(
        self, key: str, device: Optional[str]
    ) -> Tuple[Optional[float], Optional[BenchRun], int, List[float]]:
        """Most recent comparable value for ``key`` plus every older
        comparable value (used to widen the band to the observed run-to-run
        dispersion)."""
        skipped = 0
        baseline: Optional[float] = None
        run: Optional[BenchRun] = None
        older: List[float] = []
        for cand in reversed(self.history):  # newest first
            val = cand.lookup(key)
            if val is None:
                continue
            if device and cand.device and cand.device != device:
                skipped += 1
                continue
            if baseline is None:
                baseline, run = val, cand
            else:
                older.append(val)
        return baseline, run, skipped, older

    def _effective_band(self, key: str, baseline: float, older: List[float]) -> float:
        """Class band widened to the measured history spread: a leg whose
        archived runs already disagree by 8x (CPU wall-clock across container
        generations) must not be gated at ±60%, while analytic legs whose
        history is bit-identical stay at ±1%."""
        band = band_for(key, self.noise_band)
        denom = _denom_for(key, baseline)
        for val in older:
            spread = abs(val - baseline) / denom
            band = max(band, spread * self.HISTORY_SPREAD_FACTOR)
        return band

    def compare(
        self, current: Mapping[str, Any], device: Optional[str] = None
    ) -> RegressionReport:
        """Gate ``current`` (a bench record dict, nested or already flat)
        against history.  ``device`` defaults to the record's own
        ``device`` field."""
        flat = (
            {k: float(v) for k, v in current.items()}
            if current and all(isinstance(v, (int, float)) for v in current.values())
            else flatten_numeric(current)
        )
        if device is None:
            m = _DEVICE.search(json.dumps(current, default=str))
            device = m.group(1) if m else None
        comparisons: List[LegComparison] = []
        used_runs: List[str] = []
        skipped_mismatch = 0
        for key in sorted(flat):
            baseline, run, skipped, older = self._baseline_for(key, device)
            skipped_mismatch += skipped
            if baseline is None or run is None:
                continue
            cur = flat[key]
            denom = _denom_for(key, baseline)
            delta_pct = (cur - baseline) / denom * 100.0
            direction = direction_for(key)
            band = self._effective_band(key, baseline, older)
            # additive band in |baseline| units — multiplicative thresholds
            # invert for negative baselines (noise stats can dip below zero)
            if direction is None:
                verdict = "info"
            elif direction == "lower":
                verdict = "fail" if cur > baseline + band * denom + 1e-12 else "pass"
            else:
                verdict = "fail" if cur < baseline - band * denom - 1e-12 else "pass"
            if run.source not in used_runs:
                used_runs.append(run.source)
            comparisons.append(
                LegComparison(
                    key=key,
                    current=cur,
                    baseline=baseline,
                    baseline_run=run.source,
                    delta_pct=delta_pct,
                    band_pct=band,
                    direction=direction,
                    verdict=verdict,
                )
            )
        if not comparisons:
            return RegressionReport(
                verdict="no-baseline",
                comparisons=[],
                baseline_runs=[],
                device=device,
                skipped_device_mismatch=skipped_mismatch,
            )
        verdict = "fail" if any(c.verdict == "fail" for c in comparisons) else "pass"
        return RegressionReport(
            verdict=verdict,
            comparisons=comparisons,
            baseline_runs=used_runs,
            device=device,
            skipped_device_mismatch=skipped_mismatch,
        )


def check_regressions(
    current: Mapping[str, Any],
    history_dir: str = ".",
    pattern: str = DEFAULT_PATTERN,
    noise_band: float = DEFAULT_BAND,
) -> RegressionReport:
    """One-call front door: load history from ``history_dir`` and gate the
    ``current`` bench record (what ``bench.py --check-regressions`` runs)."""
    tracker = RegressionTracker(history_dir, pattern=pattern, noise_band=noise_band)
    return tracker.compare(current)
