"""torchmetrics_tpu — a TPU-native metrics framework.

Brand-new JAX/XLA re-design with the capability surface of the reference
TorchMetrics library (/root/reference): stateful metrics whose state is a
shardable ``jax.Array`` pytree, cross-device sync lowering to
``jax.lax.psum``/``all_gather`` over ICI/DCN, and a pure functional core
(`init_state`/`update_state`/`compute_state`/`merge_states`/`sync_states`)
traceable under ``jax.jit``/``pjit`` so per-step metric accumulation fuses
into the XLA step graph.
"""

from torchmetrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    RunningMean,
    RunningSum,
    SumMetric,
)
from torchmetrics_tpu.core import CompositionalMetric, Metric, Reduce

__version__ = "0.1.0"

__all__ = [
    "CatMetric",
    "CompositionalMetric",
    "MaxMetric",
    "MeanMetric",
    "Metric",
    "MinMetric",
    "Reduce",
    "RunningMean",
    "RunningSum",
    "SumMetric",
]
