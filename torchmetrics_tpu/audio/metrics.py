"""Modular audio metrics.

Reference: audio/{snr.py:35,145,244, sdr.py:37,173,282, pit.py:30, pesq.py:29,
stoi.py:29, srmr.py:37}.  Every class keeps the reference's
(sum-of-per-sample-values, count) scalar states, so distributed sync is two
psums regardless of batch shape.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality
from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training
from torchmetrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
    source_aggregated_signal_distortion_ratio,
)
from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from torchmetrics_tpu.functional.audio.srmr import (
    speech_reverberation_modulation_energy_ratio,
)
from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility


class _AveragedAudioMetric(Metric):
    """Base: (Σ per-sample value, n) states; subclass supplies ``_values``."""

    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_value", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _values(self, preds: Array, target: Array) -> Array:
        raise NotImplementedError

    def _update(self, state: State, preds: Array, target: Array) -> State:
        values = self._values(preds, target)
        return {
            "sum_value": state["sum_value"] + values.sum(),
            "total": state["total"] + values.size,
        }

    def _compute(self, state: State) -> Array:
        return state["sum_value"] / state["total"]


class SignalNoiseRatio(_AveragedAudioMetric):
    """SNR (reference audio/snr.py:35).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import SignalNoiseRatio
        >>> metric = SignalNoiseRatio()
        >>> metric.update(jnp.asarray([3.0, -0.5, 2.0, 7.0]), jnp.asarray([3.0, -0.5, 2.0, 8.0]))
        >>> round(float(metric.compute()), 4)
        18.879
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_noise_ratio(preds, target, self.zero_mean)


class ScaleInvariantSignalNoiseRatio(_AveragedAudioMetric):
    """SI-SNR (reference audio/snr.py:145)."""

    is_differentiable = True
    higher_is_better = True

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_noise_ratio(preds, target)


class ComplexScaleInvariantSignalNoiseRatio(_AveragedAudioMetric):
    """C-SI-SNR (reference audio/snr.py:244)."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return complex_scale_invariant_signal_noise_ratio(preds, target, self.zero_mean)


class SignalDistortionRatio(_AveragedAudioMetric):
    """SDR (reference audio/sdr.py:37)."""

    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        use_cg_iter: Optional[int] = None,
        filter_length: int = 512,
        zero_mean: bool = False,
        load_diag: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.use_cg_iter = use_cg_iter
        self.filter_length = filter_length
        self.zero_mean = zero_mean
        self.load_diag = load_diag

    def _values(self, preds: Array, target: Array) -> Array:
        return signal_distortion_ratio(
            preds, target, self.use_cg_iter, self.filter_length, self.zero_mean, self.load_diag
        )


class ScaleInvariantSignalDistortionRatio(_AveragedAudioMetric):
    """SI-SDR (reference audio/sdr.py:173).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.audio import ScaleInvariantSignalDistortionRatio
        >>> metric = ScaleInvariantSignalDistortionRatio()
        >>> metric.update(jnp.asarray([3.0, -0.5, 2.0, 7.0]), jnp.asarray([3.0, -0.5, 2.0, 8.0]))
        >>> round(float(metric.compute()), 4)
        25.5862
    """

    is_differentiable = True
    higher_is_better = True

    def __init__(self, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return scale_invariant_signal_distortion_ratio(preds, target, self.zero_mean)


class SourceAggregatedSignalDistortionRatio(_AveragedAudioMetric):
    """SA-SDR (reference audio/sdr.py:282)."""

    is_differentiable = True
    higher_is_better = True

    def __init__(self, scale_invariant: bool = True, zero_mean: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(scale_invariant, bool):
            raise ValueError(f"Expected argument `scale_invariant` to be a bool, but got {scale_invariant}")
        if not isinstance(zero_mean, bool):
            raise ValueError(f"Expected argument `zero_mean` to be a bool, but got {zero_mean}")
        self.scale_invariant = scale_invariant
        self.zero_mean = zero_mean

    def _values(self, preds: Array, target: Array) -> Array:
        return source_aggregated_signal_distortion_ratio(
            preds, target, self.scale_invariant, self.zero_mean
        )


class PermutationInvariantTraining(_AveragedAudioMetric):
    """PIT (reference audio/pit.py:30).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.audio import signal_noise_ratio
        >>> from torchmetrics_tpu.audio import PermutationInvariantTraining
        >>> metric = PermutationInvariantTraining(signal_noise_ratio)
        >>> preds = jnp.asarray([[[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]])
        >>> target = jnp.asarray([[[4.1, 5.0, 6.0], [1.0, 2.1, 3.0]]])  # permuted
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        35.2485
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        metric_func: Callable,
        mode: str = "speaker-wise",
        eval_func: str = "max",
        **kwargs: Any,
    ) -> None:
        from torchmetrics_tpu.core.metric import METRIC_BASE_KWARGS

        base_kwargs = {k: kwargs.pop(k) for k in list(kwargs) if k in METRIC_BASE_KWARGS}
        super().__init__(**base_kwargs)
        self.metric_func = metric_func
        self.mode = mode
        self.eval_func = eval_func
        self.metric_kwargs = kwargs  # remaining kwargs forward to metric_func

    def _values(self, preds: Array, target: Array) -> Array:
        best_metric, _ = permutation_invariant_training(
            preds, target, self.metric_func, self.mode, self.eval_func, **self.metric_kwargs
        )
        return best_metric


class PerceptualEvaluationSpeechQuality(_AveragedAudioMetric):
    """PESQ (reference audio/pesq.py:29); requires the native backend or a
    custom ``backend`` callable."""

    is_differentiable = False
    higher_is_better = True
    plot_lower_bound = -0.5
    plot_upper_bound = 4.5

    def __init__(
        self,
        fs: int,
        mode: str,
        n_processes: int = 1,
        backend: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        if mode not in ("wb", "nb"):
            raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
        self.fs = fs
        self.mode = mode
        self.backend = backend

    def _values(self, preds: Array, target: Array) -> Array:
        return jnp.atleast_1d(
            perceptual_evaluation_speech_quality(preds, target, self.fs, self.mode, backend=self.backend)
        )


class ShortTimeObjectiveIntelligibility(_AveragedAudioMetric):
    """STOI (reference audio/stoi.py:29)."""

    is_differentiable = False
    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, fs: int, extended: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.fs = fs
        self.extended = extended

    def _values(self, preds: Array, target: Array) -> Array:
        return jnp.atleast_1d(
            short_time_objective_intelligibility(preds, target, self.fs, self.extended)
        )


class SpeechReverberationModulationEnergyRatio(_AveragedAudioMetric):
    """SRMR (reference audio/srmr.py:37) — no target needed."""

    is_differentiable = False
    higher_is_better = True

    def __init__(self, fs: int, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if fs not in (8000, 16000):
            raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
        self.fs = fs

    def _update(self, state: State, preds: Array) -> State:
        values = jnp.atleast_1d(speech_reverberation_modulation_energy_ratio(preds, self.fs))
        return {
            "sum_value": state["sum_value"] + values.sum(),
            "total": state["total"] + values.size,
        }
