"""Aggregation metrics: Sum/Mean/Max/Min/Cat + running variants.

TPU-native counterpart of the reference's ``aggregation.py``
(/root/reference/src/torchmetrics/aggregation.py:30-727).  NaN handling is
expressed with ``jnp.where`` masks so ``ignore``/impute strategies stay fully
jittable; ``error``/``warn`` strategies require a host readback and therefore
only fire on the eager facade path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.utilities.data import dim_zero_cat
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utilities.prints import rank_zero_warn


class BaseAggregator(Metric):
    """Base for aggregation metrics (reference: aggregation.py:30-111).

    Args:
        fn: reduction applied between current state and new input
        default_value: initial state value
        nan_strategy: ``error`` | ``warn`` | ``ignore`` | ``disable`` | float (impute)
    """

    is_differentiable = None
    higher_is_better = None
    full_state_update = False
    # aggregators implement their own input-level NaN vocabulary
    # (error/warn/ignore/disable/float-impute) — opt out of the base
    # Metric's state-level guard so the two never double-apply
    __handles_nan_strategy__ = True

    def __init__(
        self,
        state_name: str,
        default_value: Union[Array, list],
        dist_reduce_fx: str,
        nan_strategy: Union[str, float] = "error",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed = ("error", "warn", "ignore", "disable")
        if not (isinstance(nan_strategy, (int, float)) and not isinstance(nan_strategy, bool)) and nan_strategy not in allowed:
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.state_name = state_name
        self.add_state(state_name, default=default_value, dist_reduce_fx=dist_reduce_fx)

    def _handle_nan(self, x: Array) -> Array:
        """Apply the NaN strategy; error/warn need a host readback (eager only)."""
        if self.nan_strategy == "disable":
            return x
        if isinstance(self.nan_strategy, (int, float)) and not isinstance(self.nan_strategy, bool):
            return jnp.where(jnp.isnan(x), jnp.asarray(self.nan_strategy, dtype=x.dtype), x)
        if self.nan_strategy == "ignore":
            return x  # masking handled per-subclass (needs the identity element)
        # error / warn: host readback — only valid outside jit
        try:
            has_nan = bool(jnp.isnan(x).any())
        except Exception:  # traced: silently fall through (jit path)
            return x
        if has_nan:
            if self.nan_strategy == "error":
                raise RuntimeError("Encountered `nan` values in tensor")
            rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
        return x

    def _cast_input(self, x: Union[float, Array]) -> Array:
        x = jnp.asarray(x, dtype=self.dtype)
        return self._handle_nan(jnp.atleast_1d(x) if x.ndim == 0 else x)

    def _nan_mask_reduce(self, x: Array, reduce_fn: Callable, identity: float) -> Array:
        """Reduce ``x`` with NaNs replaced by the reduction identity."""
        if self.nan_strategy == "disable":
            return reduce_fn(x)
        return reduce_fn(jnp.where(jnp.isnan(x), jnp.asarray(identity, dtype=x.dtype), x))

    def _compute(self, state: State) -> Array:
        value = state[self.state_name]
        if isinstance(value, tuple):
            return dim_zero_cat(value)
        return value


class MaxMetric(BaseAggregator):
    """Running max (reference: aggregation.py:114-218).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MaxMetric
        >>> metric = MaxMetric()
        >>> metric.update(jnp.asarray([1.0, 5.0, 3.0]))
        >>> round(float(metric.compute()), 4)
        5.0
    """

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max_value", jnp.asarray(-jnp.inf), "max", nan_strategy, **kwargs)

    def _update(self, state: State, value: Union[float, Array]) -> State:
        value = self._cast_input(value)
        return {"max_value": jnp.maximum(state["max_value"], self._nan_mask_reduce(value, jnp.max, -jnp.inf))}


class MinMetric(BaseAggregator):
    """Running min (reference: aggregation.py:219-323)."""

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min_value", jnp.asarray(jnp.inf), "min", nan_strategy, **kwargs)

    def _update(self, state: State, value: Union[float, Array]) -> State:
        value = self._cast_input(value)
        return {"min_value": jnp.minimum(state["min_value"], self._nan_mask_reduce(value, jnp.min, jnp.inf))}


class SumMetric(BaseAggregator):
    """Running sum (reference: aggregation.py:324-428).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = SumMetric()
        >>> metric.update(jnp.asarray([1.0, 2.0, 3.0]))
        >>> round(float(metric.compute()), 4)
        6.0
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum_value", jnp.zeros(()), "sum", nan_strategy, **kwargs)

    def _update(self, state: State, value: Union[float, Array]) -> State:
        value = self._cast_input(value)
        return {"sum_value": state["sum_value"] + self._nan_mask_reduce(value, jnp.sum, 0.0)}


class CatMetric(BaseAggregator):
    """Concatenate all seen values (reference: aggregation.py:429-492)."""

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("value", [], "cat", nan_strategy, **kwargs)

    def _update(self, state: State, value: Union[float, Array]) -> State:
        value = self._cast_input(value)
        if self.nan_strategy == "ignore":
            value = value[~jnp.isnan(value)]
        return {"value": tuple(state["value"]) + (value,)}


class MeanMetric(BaseAggregator):
    """Weighted running mean (reference: aggregation.py:493-615).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.aggregation import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(jnp.asarray([1.0, 2.0, 3.0]))
        >>> round(float(metric.compute()), 4)
        2.0
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("mean_value", jnp.zeros(()), "sum", nan_strategy, **kwargs)
        # weight stays float32: fractional user weights are legal, so int
        # widening is off the table.  With unit weights the float32 sum
        # stagnates at 2**24 (~16.7M values) — a documented limitation
        # (README "Numerics analysis"), not a silent one.
        self.add_state("weight", default=jnp.zeros(()), dist_reduce_fx="sum")  # tmt: ignore[TMT014] -- float weight sum: fractional weights are legal; f32 stagnates at 2**24 unit-weight values (documented)
        self.state_name = "mean_value"

    def _update(self, state: State, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> State:
        value = self._cast_input(value)
        weight = jnp.broadcast_to(jnp.asarray(weight, dtype=self.dtype), value.shape)
        nan_mask = jnp.isnan(value)
        if self.nan_strategy != "disable":
            weight = jnp.where(nan_mask, 0.0, weight)
            value = jnp.where(nan_mask, 0.0, value)
        return {
            "mean_value": state["mean_value"] + jnp.sum(value * weight),
            "weight": state["weight"] + jnp.sum(weight),
        }

    def _compute(self, state: State) -> Array:
        return state["mean_value"] / jnp.maximum(state["weight"], jnp.finfo(self.dtype).eps)


class RunningMean(MeanMetric):
    """Mean over a sliding window of the last ``window`` updates.

    Reference (aggregation.py:616-672) duplicates states × window and
    round-robin-overwrites; here the ring buffer is two fixed-shape arrays —
    static shapes, so it jits.
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(nan_strategy=nan_strategy, **kwargs)
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Argument `window` should be a positive integer but got {window}")
        self.window = window
        self.add_state("ring_value", default=jnp.zeros(window), dist_reduce_fx=None)
        self.add_state("ring_weight", default=jnp.zeros(window), dist_reduce_fx=None)

    def _update(self, state: State, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> State:
        value = self._cast_input(value)
        weight = jnp.broadcast_to(jnp.asarray(weight, dtype=self.dtype), value.shape)
        nan_mask = jnp.isnan(value)
        if self.nan_strategy != "disable":
            weight = jnp.where(nan_mask, 0.0, weight)
            value = jnp.where(nan_mask, 0.0, value)
        slot = jnp.mod(state["_n"], self.window)
        return {
            "mean_value": state["mean_value"],
            "weight": state["weight"],
            "ring_value": state["ring_value"].at[slot].set(jnp.sum(value * weight)),
            "ring_weight": state["ring_weight"].at[slot].set(jnp.sum(weight)),
        }

    def _compute(self, state: State) -> Array:
        return jnp.sum(state["ring_value"]) / jnp.maximum(jnp.sum(state["ring_weight"]), jnp.finfo(self.dtype).eps)


class RunningSum(SumMetric):
    """Sum over a sliding window of the last ``window`` updates (reference: aggregation.py:673-727)."""

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(nan_strategy=nan_strategy, **kwargs)
        if not (isinstance(window, int) and window > 0):
            raise ValueError(f"Argument `window` should be a positive integer but got {window}")
        self.window = window
        self.add_state("ring_value", default=jnp.zeros(window), dist_reduce_fx=None)

    def _update(self, state: State, value: Union[float, Array]) -> State:
        value = self._cast_input(value)
        slot = jnp.mod(state["_n"], self.window)
        return {
            "sum_value": state["sum_value"],
            "ring_value": state["ring_value"].at[slot].set(self._nan_mask_reduce(value, jnp.sum, 0.0)),
        }

    def _compute(self, state: State) -> Array:
        return jnp.sum(state["ring_value"])
