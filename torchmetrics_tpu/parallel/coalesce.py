"""Collective coalescing: the sync planner behind every bucketed state sync.

Per-leaf sync (one ``psum``/``pmax``/... per state leaf — the pre-coalescing
``sync_state`` loop) pays one collective launch per leaf per metric per step.
BENCH_r05 showed where that bites: ``MetricCollection(Acc, F1, AUROC)`` moves
13 tiny collectives per step, and FID's scalar counters each ride a full ring
round-trip of their own.  DDP training stacks solved the same problem years
ago with gradient bucketing — flatten many small tensors into one flat
buffer per dtype and issue ONE collective per bucket — and the technique
transfers directly to metric state because every psum-family reduction is
elementwise.

This module is the single place such bucketing lives:

* :func:`build_sync_plan` / :func:`apply_sync_plan` — partition the
  psum-family leaves of one or many states into buckets keyed by
  ``(dtype, reduction-class)`` where the class is sum (MEAN rides the sum
  bucket and divides by the static axis size afterwards — bit-identical to
  ``pmean``), min, or max; flatten each bucket to one 1-D buffer; issue one
  collective per bucket; unflatten.  The plan is a *static* function of the
  reduction table + leaf specs, so it is rebuilt only while XLA traces and
  folds into the existing compile-cache fingerprints with zero extra cache
  entries or retraces.
* :func:`coalesced_sync_state` — drop-in replacement for the per-leaf sync
  loop (``Metric.sync_states`` and ``parallel.sync.sync_state`` route here).
* :func:`coalesced_metric_sync` — the cross-metric variant: ALL compute-group
  leaders of a ``MetricCollection`` share one bucket plan, so the whole
  collection syncs in as few collectives as it has distinct
  (dtype, class) pairs (2 for Acc+F1+AUROC: one f32 sum, one i32 sum).
* :func:`coalesced_host_sync` — the DCN stage of the hierarchical two-stage
  reduce: one ``process_allgather`` per bucket on the *already ICI-reduced*
  copy, so DCN moves one host-level copy instead of one per device
  (~``n_local_devices``× fewer bytes than a flat device-level sync).
* :class:`SyncPolicy` / :class:`SyncStepper` — sync cadence control:
  accumulate locally (collective-free) for ``every_n_steps`` and run the
  bucketed collective only on sync steps or at ``compute()``.  Sound because
  every reduction in the table is associative; exact (bit-for-bit) for
  sum/min/max tables whose sums are exactly representable (integer-valued
  counts — Accuracy/F1/AUROC confusion statistics).

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.core.reductions import Reduce
    >>> from torchmetrics_tpu.parallel.coalesce import build_sync_plan
    >>> state = {"tp": jnp.zeros((5,)), "fp": jnp.zeros((5,)), "lo": jnp.zeros(()),
    ...          "_n": jnp.zeros((), jnp.int32)}
    >>> table = {"tp": Reduce.SUM, "fp": Reduce.SUM, "lo": Reduce.MIN}
    >>> plan = build_sync_plan([(table, state)])
    >>> [(b.dtype, b.op, len(b.slots)) for b in plan.buckets]
    [('float32', 'min', 1), ('float32', 'sum', 2), ('int32', 'sum', 1)]
    >>> plan.n_collectives  # 3 buckets instead of 4 per-leaf collectives
    3
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.reductions import Reduce, SketchReduce, host_sync_leaf, sync_leaf
from torchmetrics_tpu.parallel.compress import (
    CompressionConfig,
    CompressionSpec,
    compressed_psum,
    compressed_psum_scatter,
    compression_spec_for,
    host_dequantize_int8,
    host_quantize_int8,
    predicted_error_bound,
)

__all__ = [
    "Bucket",
    "CompressionConfig",
    "CompressionSpec",
    "SyncAdvisor",
    "SyncPlan",
    "SyncPolicy",
    "SyncStepper",
    "apply_sync_plan",
    "build_sync_plan",
    "bucketed_collective_count",
    "cadence_stepper",
    "coalesced_host_sync",
    "coalesced_metric_sync",
    "coalesced_sync_state",
    "flush_sync",
    "per_leaf_collective_count",
    "plan_for_metric",
    "plan_for_metrics",
]

State = Dict[str, Any]

_N = "_n"
_NONFINITE = "_nonfinite"
_RESERVED = (_N, _NONFINITE)

#: reductions that lower to a single elementwise all-reduce and can therefore
#: share a flat bucket buffer; MEAN rides the sum bucket (see ``_Slot.mean``)
_PSUM_FAMILY = (Reduce.SUM, Reduce.MEAN, Reduce.MAX, Reduce.MIN)
_OP_OF = {Reduce.SUM: "sum", Reduce.MEAN: "sum", Reduce.MAX: "max", Reduce.MIN: "min"}
_COLLECTIVE = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}
_HOST_REDUCE = {"sum": lambda g: g.sum(0), "max": lambda g: g.max(0), "min": lambda g: g.min(0)}


# ------------------------------------------------------------------- planning
@dataclass(frozen=True)
class _Slot:
    """One leaf's position inside a bucket."""

    entry: int  # index into the entries/states sequence
    name: str
    shape: Tuple[int, ...]
    size: int
    mean: bool  # MEAN leaf riding the sum bucket: divide by axis size after
    #: leaf dimension scattered across the sync axis (sharded SUM leaves
    #: riding a reduce-scatter bucket); ``None`` for replicated leaves
    shard_axis: Optional[int] = None


@dataclass(frozen=True)
class Bucket:
    """All same-(dtype, op) psum-family leaves fused into one collective.

    ``compression`` is ``None`` for exact buckets (the default — plans built
    without a :class:`CompressionConfig` are field-for-field identical to
    pre-compression plans) and a :class:`CompressionSpec` when the planner
    elected to quantize this bucket's wire payload.
    """

    dtype: str
    op: str  # "sum" | "min" | "max"
    slots: Tuple[_Slot, ...]
    compression: Optional[CompressionSpec] = None
    #: sharded buckets lower to ``lax.psum_scatter`` — each replica keeps
    #: only its block of the sum ((n-1)/n·B wire bytes instead of the ring
    #: all-reduce's 2(n-1)/n·B, B/n resident HBM); always ``False`` for
    #: plans built without sharding specs (field-for-field identical plans)
    sharded: bool = False

    @property
    def size(self) -> int:
        return sum(s.size for s in self.slots)

    @property
    def n_collectives(self) -> int:
        """Collectives this bucket issues (the int8 exchange is two-phase;
        sharded buckets always issue exactly one — the int8 reduce-scatter
        drops the replicating ``all_gather`` phase)."""
        if self.sharded:
            return 1
        return 1 if self.compression is None else self.compression.n_collectives


@dataclass(frozen=True)
class SyncPlan:
    """Static bucketing of one or many states under their reduction tables.

    Depends only on the reduction tables and the leaves' shapes/dtypes — the
    same facts the compile-cache keys already fingerprint — so building it
    inside a traced step body can never add cache entries or retraces.
    """

    buckets: Tuple[Bucket, ...]
    #: leaves synced individually through :func:`core.reductions.sync_leaf`:
    #: cat/none/callable reductions, tuple (list-state) leaves, and
    #: integer-dtype MEAN leaves (``pmean`` true-divides them to float;
    #: bucketing must never change a result dtype)
    passthrough: Tuple[Tuple[int, str, Any], ...]
    n_entries: int
    n_passthrough_collectives: int

    @property
    def n_collectives(self) -> int:
        """Collectives one sync under this plan launches."""
        return sum(b.n_collectives for b in self.buckets) + self.n_passthrough_collectives

    def bucket_sizes(self) -> Dict[str, int]:
        """``{"dtype/op": element count}`` per bucket (accounting surface)."""
        return {f"{b.dtype}/{b.op}": b.size for b in self.buckets}


def bucket_scatter_size(bucket: Bucket, n_devices: int) -> int:
    """Element count a bucket actually moves: its logical size for
    replicated buckets, the divisibility-padded size for sharded buckets
    (each slot's shard dimension rounds up to a multiple of ``n_devices``
    before the ``psum_scatter``)."""
    if not bucket.sharded:
        return bucket.size
    n = max(int(n_devices), 1)
    total = 0
    for s in bucket.slots:
        ax = s.shard_axis or 0
        dim = s.shape[ax]
        tail = s.size // max(dim, 1)
        total += (-(-dim // n) * n) * tail
    return total


def _reduce_for(name: str, reductions: Mapping[str, Any]) -> Any:
    if name in _RESERVED:  # reserved counters: always summed
        return Reduce.SUM
    try:
        return reductions[name]
    except KeyError:
        raise KeyError(
            f"state leaf {name!r} has no entry in the reduction table "
            f"(known: {sorted(reductions)}) and is not a reserved counter"
        ) from None


def build_sync_plan(
    entries: Sequence[Tuple[Mapping[str, Any], Mapping[str, Any]]],
    compression: Optional[CompressionConfig] = None,
    shardings: Optional[Sequence[Optional[Mapping[str, Any]]]] = None,
) -> SyncPlan:
    """Plan one coalesced sync over ``entries`` = [(reduction table, state), ...].

    Multiple entries (one per compute-group leader) share buckets — the
    cross-metric fusion :func:`coalesced_metric_sync` builds on.  Bucket
    order is sorted by (dtype, op) and slot order follows entry/table order,
    both deterministic, so repeated traces of the same configuration emit an
    identical graph.

    ``compression`` opts eligible buckets into quantized wire payloads: only
    float32 *sum* buckets at or above ``compression.min_bucket_bytes`` whose
    declared error bound fits ``compression.error_budget`` get a
    :class:`CompressionSpec`; integer (count) buckets, min/max buckets, and
    every passthrough leaf always stay exact.  ``None`` (the default) yields
    a plan identical to the pre-compression planner.

    ``shardings`` (aligned with ``entries``; each element ``None`` or a
    ``{leaf: ShardSpec}`` mapping) routes sharded SUM leaves into dedicated
    ``(dtype, op, sharded)`` buckets lowered to ``lax.psum_scatter`` —
    every replica keeps only its block of the sum.  ``None`` (the default)
    yields plans field-for-field identical to the pre-sharding planner.
    """
    groups: Dict[Tuple[str, str, bool], List[_Slot]] = {}
    passthrough: List[Tuple[int, str, Any]] = []
    n_pass = 0
    for e, (reductions, state) in enumerate(entries):
        for name, value in state.items():
            reduce = _reduce_for(name, reductions)
            if isinstance(value, tuple):
                passthrough.append((e, name, reduce))
                n_pass += len(value)
                continue
            if isinstance(reduce, SketchReduce):
                # sketch leaves with an elementwise merge ride the matching
                # fused dtype bucket exactly like SUM/MAX/MIN leaves; the
                # structural ones (reservoirs) sync individually as one
                # fixed-shape gather + in-graph combine
                if reduce.bucket_op in _COLLECTIVE:
                    shape = tuple(int(d) for d in value.shape)
                    slot = _Slot(
                        entry=e,
                        name=name,
                        shape=shape,
                        size=int(np.prod(shape, dtype=np.int64)),
                        mean=False,
                    )
                    groups.setdefault(
                        (str(jnp.dtype(value.dtype)), reduce.bucket_op, False), []
                    ).append(slot)
                else:
                    passthrough.append((e, name, reduce))
                    n_pass += reduce.n_sync_gathers
                continue
            if callable(reduce) and not isinstance(reduce, Reduce):
                passthrough.append((e, name, reduce))
                n_pass += 1
                continue
            if reduce not in _PSUM_FAMILY:
                passthrough.append((e, name, reduce))
                n_pass += 1
                continue
            dtype = jnp.dtype(value.dtype)
            if reduce == Reduce.MEAN and not jnp.issubdtype(dtype, jnp.inexact):
                passthrough.append((e, name, reduce))
                n_pass += 1
                continue
            shape = tuple(int(d) for d in value.shape)
            shard_spec = None
            if reduce == Reduce.SUM and shardings is not None and shardings[e]:
                shard_spec = shardings[e].get(name)
            slot = _Slot(
                entry=e,
                name=name,
                shape=shape,
                size=int(np.prod(shape, dtype=np.int64)),
                mean=reduce == Reduce.MEAN,
                shard_axis=None if shard_spec is None else int(shard_spec.axis),
            )
            groups.setdefault((str(dtype), _OP_OF[reduce], shard_spec is not None), []).append(slot)
    buckets = []
    for (dt, op, sharded), slots in sorted(groups.items()):
        nbytes = sum(s.size for s in slots) * jnp.dtype(dt).itemsize
        spec = compression_spec_for(dt, op, nbytes, compression)
        buckets.append(
            Bucket(dtype=dt, op=op, slots=tuple(slots), compression=spec, sharded=sharded)
        )
    buckets = tuple(buckets)
    return SyncPlan(
        buckets=buckets,
        passthrough=tuple(passthrough),
        n_entries=len(entries),
        n_passthrough_collectives=n_pass,
    )


def _apply_sharded_bucket(
    bucket: Bucket,
    states: Sequence[Mapping[str, Any]],
    axis_name: str,
    w: Optional[Any],
    outs: List[State],
) -> None:
    """Lower one sharded SUM bucket to a single ``lax.psum_scatter``.

    Per slot: move the shard axis to the front, zero-pad it (the SUM
    identity) to a multiple of the mesh-axis size ``n``, and view it as
    ``(n, k)`` — row ``i`` is the flattened block device ``i`` will own.
    Slots concatenate along the block dimension so the whole bucket rides
    ONE collective; ``psum_scatter`` leaves device ``i`` holding the exact
    cross-replica sum of block ``i``, which slices back into per-slot shard
    shapes.  Wire bytes per chip: ``(n-1)/n·B`` instead of the ring
    all-reduce's ``2(n-1)/n·B``; resident HBM per chip: ``B/n``.

    The quarantine mask multiplies the contribution before the collective
    (zeros are the SUM identity), and bf16/int8 compression applies to the
    scatter payload per-bucket exactly as on the all-reduce path
    (:func:`~torchmetrics_tpu.parallel.compress.compressed_psum_scatter`).
    """
    # Under shard_map the axis size constant-folds to a concrete Python int.
    n = jax.lax.psum(1, axis_name)
    mats = []
    layout = []  # (slot, moved_tail_shape, padded_dim, block_cols)
    for s in bucket.slots:
        x = states[s.entry][s.name]
        ax = s.shard_axis or 0
        x = jnp.moveaxis(x, ax, 0)
        d = int(x.shape[0])
        pad = (-d) % n
        if pad:
            widths = [(0, 0)] * x.ndim
            widths[0] = (0, pad)
            x = jnp.pad(x, widths)
        tail = tuple(int(t) for t in x.shape[1:])
        mat = x.reshape((n, -1))
        mats.append(mat)
        layout.append((s, tail, d + pad, int(mat.shape[1])))
    mat = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
    if w is not None:
        mat = mat * w.astype(mat.dtype)
    if bucket.compression is not None:
        with jax.named_scope(
            f"tm_tpu/compress/{bucket.compression.mode}_scatter_{bucket.dtype}"
        ):
            red = compressed_psum_scatter(mat, axis_name, bucket.compression)
    else:
        with jax.named_scope(f"tm_tpu/coalesce/scatter_{bucket.dtype}"):
            red = jax.lax.psum_scatter(mat, axis_name, scatter_dimension=0, tiled=False)
    offset = 0
    for s, tail, padded_dim, cols in layout:
        seg = red if len(layout) == 1 else jax.lax.slice_in_dim(red, offset, offset + cols)
        seg = seg.reshape((padded_dim // n,) + tail)
        outs[s.entry][s.name] = jnp.moveaxis(seg, 0, s.shard_axis or 0)
        offset += cols


def _mask_identity(dtype: Any, op: str) -> Any:
    """The reduction identity a quarantined replica contributes to a
    min/max bucket: +inf/iinfo.max for min, -inf/iinfo.min for max."""
    dt = jnp.dtype(dtype)
    if jnp.issubdtype(dt, jnp.inexact):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, dt)
    info = jnp.iinfo(dt)
    return jnp.asarray(info.max if op == "min" else info.min, dt)


def apply_sync_plan(
    plan: SyncPlan,
    states: Sequence[Mapping[str, Any]],
    axis_name: str,
    weight: Optional[Any] = None,
) -> List[State]:
    """Run one coalesced sync (pure; call under shard_map/pmap).

    Per bucket: ravel every slot, concatenate, ONE collective, slice back.
    MEAN slots divide the summed segment by the static mesh-axis size —
    ``jax.lax.psum(1, axis)`` constant-folds, and ``pmean`` itself lowers to
    exactly ``psum(x) / psum(1)``, so the result is bit-identical to the
    per-leaf ``pmean`` it replaces.

    ``weight`` — ``None`` (default) or this replica's traced 0/1 scalar —
    is the degraded-mode quarantine mask.  ``None`` traces exactly the graph
    above.  With a weight: sum buckets contribute ``flat * w`` (a zeroed
    replica adds the sum identity), min/max buckets contribute the
    reduction identity where ``w == 0``, and MEAN slots divide by
    ``psum(w)`` (clamped to 1) — the mean over *surviving* replicas.  The
    mask is a data input, so flipping the quarantine set re-runs the same
    executable: zero retraces.  Passthrough leaves (cat/custom/structural
    sketch) have no maskable collective and are rejected.
    """
    if weight is not None and plan.passthrough:
        names = sorted({name for _, name, _ in plan.passthrough})
        raise ValueError(
            f"masked (quarantined) sync cannot exclude a replica from passthrough "
            f"leaves {names}: cat/custom/structural-sketch leaves gather raw "
            "per-replica payloads rather than reducing them. Quarantine supports "
            "psum-family state only."
        )
    outs: List[State] = [{} for _ in range(plan.n_entries)]
    w = None if weight is None else jnp.asarray(weight).reshape(())
    for bucket in plan.buckets:
        if bucket.sharded:
            _apply_sharded_bucket(bucket, states, axis_name, w, outs)
            continue
        parts = [states[s.entry][s.name].reshape((s.size,)) for s in bucket.slots]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if w is not None:
            if bucket.op == "sum":
                flat = flat * w.astype(flat.dtype)
            else:
                flat = jnp.where(w > 0, flat, _mask_identity(bucket.dtype, bucket.op))
        if bucket.compression is not None:
            with jax.named_scope(
                f"tm_tpu/compress/{bucket.compression.mode}_{bucket.op}_{bucket.dtype}"
            ):
                red = compressed_psum(flat, axis_name, bucket.compression)
        else:
            with jax.named_scope(f"tm_tpu/coalesce/{bucket.op}_{bucket.dtype}"):
                red = _COLLECTIVE[bucket.op](flat, axis_name)
        offset = 0
        for s in bucket.slots:
            seg = red if len(bucket.slots) == 1 else jax.lax.slice_in_dim(red, offset, offset + s.size)
            seg = seg.reshape(s.shape)
            if s.mean:
                if w is None:
                    seg = seg / jax.lax.psum(1, axis_name)
                else:
                    quorum = jax.lax.psum(w.astype(seg.dtype), axis_name)
                    seg = seg / jnp.maximum(quorum, jnp.asarray(1, seg.dtype))
            outs[s.entry][s.name] = seg
            offset += s.size
    for e, name, reduce in plan.passthrough:
        outs[e][name] = sync_leaf(reduce, states[e][name], axis_name)
    return outs


def coalesced_sync_state(
    state: Mapping[str, Any],
    reductions: Mapping[str, Union[Reduce, Callable]],
    axis_name: str = "data",
    compression: Optional[CompressionConfig] = None,
    weight: Optional[Any] = None,
    shardings: Optional[Mapping[str, Any]] = None,
) -> State:
    """Bucketed replacement for the per-leaf sync loop (pure, in-graph).

    Every key of ``state`` must be in the reduction table or be a reserved
    counter (``_n``/``_nonfinite``, always summed) — the same contract the
    per-leaf ``sync_state`` enforced.  ``compression=None`` (the default)
    traces the exact planner graph bit-for-bit.  ``weight`` is the
    per-replica quarantine mask (see :func:`apply_sync_plan`).
    ``shardings`` (``{leaf: ShardSpec}``) routes sharded SUM leaves to the
    reduce-scatter lowering — those come back shard-shaped per device.
    """
    plan = build_sync_plan(
        [(reductions, state)],
        compression=compression,
        shardings=None if not shardings else [shardings],
    )
    return apply_sync_plan(plan, [state], axis_name, weight=weight)[0]


def _metric_entry(metric: Any, state: Mapping[str, Any]) -> Tuple[Mapping[str, Any], State]:
    """The (reduction table, synced-leaf subset) entry ``sync_states`` plans
    over: every registered leaf plus the reserved ``_n`` counter."""
    sub: State = {name: state[name] for name in metric._reductions}
    sub[_N] = state[_N]
    return metric._reductions, sub


def _metric_shardings(metric: Any) -> Optional[Mapping[str, Any]]:
    """The metric's per-leaf ShardSpec table, or ``None`` when unsharded."""
    return getattr(metric, "_state_shardings", None) or None


def plan_for_metric(
    metric: Any,
    state: Optional[Mapping[str, Any]] = None,
    compression: Optional[CompressionConfig] = None,
) -> SyncPlan:
    """Introspection hook: the exact :class:`SyncPlan` one ``sync_states``
    call on ``metric`` builds (``state`` defaults to the live accumulator).

    The analysis auditor (``analysis/audit.py``) compares this plan's
    ``n_collectives`` against the collective primitives actually present in
    the traced sync jaxpr — closing the loop between the planner's cost
    model and what XLA lowers.
    """
    if state is None:
        state = metric._state
    return build_sync_plan(
        [_metric_entry(metric, state)],
        compression=compression,
        shardings=[_metric_shardings(metric)],
    )


def plan_for_metrics(
    metrics: Sequence[Any],
    states: Sequence[Mapping[str, Any]],
    compression: Optional[CompressionConfig] = None,
) -> Tuple[SyncPlan, Tuple[int, ...]]:
    """Cross-metric introspection hook: the shared bucket plan for the
    coalescible (standard-``sync_states``) subset of ``metrics``.

    Returns ``(plan, standard_indices)``; metrics that override
    ``sync_states`` keep their own aggregation and are excluded — exactly
    the partition :func:`coalesced_metric_sync` executes.
    """
    from torchmetrics_tpu.core.metric import Metric

    standard = tuple(
        i for i, m in enumerate(metrics) if type(m).sync_states is Metric.sync_states
    )
    entries = [_metric_entry(metrics[i], states[i]) for i in standard]
    shardings = [_metric_shardings(metrics[i]) for i in standard]
    if not any(shardings):
        shardings = None  # pre-sharding plans stay field-for-field identical
    return build_sync_plan(entries, compression=compression, shardings=shardings), standard


def coalesced_metric_sync(
    metrics: Sequence[Any],
    states: Sequence[Mapping[str, Any]],
    axis_name: str,
    compression: Optional[CompressionConfig] = None,
    weight: Optional[Any] = None,
) -> List[State]:
    """Sync several metrics' states with ONE cross-metric bucket plan.

    Replicates ``Metric.sync_states`` semantics per metric (reduction-table
    leaves + summed ``_n`` + recomputed ``_nonfinite`` for guarded metrics).
    Metrics that *override* ``sync_states`` (streaming moments, wrapper
    fan-out) keep their own aggregation and sync individually — coalescing
    leaf-wise would be silently wrong for them.  ``weight`` is the
    per-replica quarantine mask (see :func:`apply_sync_plan`).
    """
    from torchmetrics_tpu.core.guards import count_nonfinite

    plan, standard = plan_for_metrics(metrics, states, compression=compression)
    entries = [_metric_entry(metrics[i], states[i]) for i in standard]
    synced = apply_sync_plan(plan, [e[1] for e in entries], axis_name, weight=weight)
    out: List[Optional[State]] = [None] * len(metrics)
    for i, st in zip(standard, synced):
        if metrics[i]._guard_strategy in ("warn", "error"):
            st[_NONFINITE] = count_nonfinite(st)
        out[i] = st
    for i, m in enumerate(metrics):
        if out[i] is None:
            if weight is None:
                out[i] = m.sync_states(states[i], axis_name)
            else:
                out[i] = m.sync_states(states[i], axis_name, None, weight)
    return out  # type: ignore[return-value]


# ---------------------------------------------------------------- accounting
def bucketed_collective_count(
    reductions: Mapping[str, Any],
    state: Mapping[str, Any],
    compression: Optional[CompressionConfig] = None,
    shardings: Optional[Mapping[str, Any]] = None,
) -> int:
    """Collectives one coalesced sync of ``state`` launches (telemetry model)."""
    return build_sync_plan(
        [(reductions, state)],
        compression=compression,
        shardings=None if not shardings else [shardings],
    ).n_collectives


def per_leaf_collective_count(
    reductions: Mapping[str, Any], state: Mapping[str, Any]
) -> int:
    """Collectives the pre-coalescing per-leaf sync loop would launch."""
    n = 0
    for name, value in state.items():
        _reduce_for(name, reductions)  # validate, same contract
        n += len(value) if isinstance(value, tuple) else 1
    return n


# ------------------------------------------------------- hierarchical (DCN)
def _mesh_is_process_local(mesh: Any) -> bool:
    """True when every mesh device belongs to this process — the in-graph
    collective then reduced over ICI only and a DCN stage is still needed."""
    me = jax.process_index()
    return all(d.process_index == me for d in mesh.devices.flat)


def coalesced_host_sync(
    state: Mapping[str, Any],
    reductions: Mapping[str, Union[Reduce, Callable]],
    *,
    n_processes: Optional[int] = None,
    allgather: Optional[Callable[[Any], Any]] = None,
    compression: Optional[CompressionConfig] = None,
    owner: Optional[Any] = None,
) -> State:
    """Cross-process (DCN) sync with one ``process_allgather`` per bucket.

    Stage 2 of the hierarchical two-stage reduce: called on a state that is
    already reduced within the host over ICI, it moves ONE host-level copy
    per bucket across DCN instead of one copy per leaf per device.
    Passthrough leaves (cat/none/callable/tuple/int-mean) keep the per-leaf
    :func:`core.reductions.host_sync_leaf` lowering.

    ``compression`` shrinks the DCN payload of eligible buckets: bf16 ships a
    half-width gather; int8 quantizes once per process with per-chunk scales
    and dequantize-sums on the host (a single quantization stage — DCN hops
    are where compression pays the most).  Exact by default.

    ``n_processes``/``allgather`` are injectable for single-process testing;
    by default they resolve to ``jax.process_count()`` and
    ``multihost_utils.process_allgather``.

    ``owner`` (a metric, optional) attributes the *passthrough* leg — the
    gather-family leaves that cross DCN raw instead of reducing — to that
    metric's telemetry: while the gather plane is armed
    (``observability.gathers.enable_gather_telemetry``) the passthrough loop
    is timed block-until-ready and lands in per-bucket ``gather/<leaf>``
    ``measured_us`` rows with the flat and granule-tiled byte models, the
    same contract as the deferred ragged gather's measurement hook.
    """
    plan = build_sync_plan([(reductions, state)], compression=compression)  # validates leaf names
    n_proc = jax.process_count() if n_processes is None else int(n_processes)
    if n_proc == 1:
        return dict(state)
    if allgather is None:  # pragma: no cover - exercised on real multi-host
        from jax.experimental import multihost_utils

        allgather = multihost_utils.process_allgather
    out: State = {}
    for bucket in plan.buckets:
        parts = [jnp.asarray(state[s.name]).reshape((s.size,)) for s in bucket.slots]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        spec = bucket.compression
        if spec is not None and spec.mode == "bf16":
            gathered = jnp.asarray(allgather(flat.astype(jnp.bfloat16)))
            red = gathered.astype(flat.dtype).sum(0)
        elif spec is not None and spec.mode == "int8":
            packed = host_quantize_int8(np.asarray(flat), spec.chunk)
            gathered = np.asarray(allgather(jnp.asarray(packed)))  # (n_proc, packed_bytes)
            red = jnp.asarray(
                sum(
                    host_dequantize_int8(gathered[p], bucket.size, spec.chunk)
                    for p in range(gathered.shape[0])
                )
            )
        else:
            gathered = jnp.asarray(allgather(flat))  # (n_proc, bucket_size)
            red = _HOST_REDUCE[bucket.op](gathered)
        offset = 0
        for s in bucket.slots:
            seg = red if len(bucket.slots) == 1 else red[offset : offset + s.size]
            seg = seg.reshape(s.shape)
            if s.mean:
                seg = seg / n_proc
            out[s.name] = seg
            offset += s.size
    if plan.passthrough:
        from torchmetrics_tpu.observability import registry as _telemetry

        measuring = (
            owner is not None and _telemetry.enabled() and _telemetry.gather_armed()
        )
        t0 = time.perf_counter() if measuring else 0.0  # tmt: ignore[TMT006] -- measured DCN gather cost at the host boundary; outside any traced graph
        for _, name, reduce in plan.passthrough:
            out[name] = host_sync_leaf(reduce, state[name])
        if measuring:
            jax.block_until_ready({name: out[name] for _, name, _ in plan.passthrough})
            measured_s = time.perf_counter() - t0  # tmt: ignore[TMT006] -- measured DCN gather cost at the host boundary; outside any traced graph
            leaf_sizes = {}
            for _, name, _ in plan.passthrough:
                elems = nbytes = 0
                for v in jax.tree.leaves(state[name]):
                    elems += int(getattr(v, "size", 1))
                    nbytes += int(getattr(v, "size", 1)) * int(
                        getattr(getattr(v, "dtype", None), "itemsize", 8)
                    )
                leaf_sizes[name] = (elems, nbytes)
            _telemetry.record_measured_gather(owner, leaf_sizes, n_proc, measured_s)
            _telemetry.record_sync_wait(measured_s)
    return out


# ------------------------------------------------------------------- cadence
@dataclass(frozen=True)
class SyncPolicy:
    """When the cross-device collective runs.

    ``SyncPolicy()`` / ``SyncPolicy(every_n_steps=1)`` syncs every step (the
    default behavior without a policy).  ``every_n_steps=k`` accumulates
    locally with the merge table for ``k`` steps and syncs on every ``k``-th;
    ``at_compute=True`` defers the only collective to ``compute()``.  Sound
    because every reduction in the table is associative; deferral changes
    float summation *order*, so it is bit-exact for integer-valued sum
    states (classification counts) but may differ in final ulps for
    mean-style float accumulators.

    ``compression`` additionally opts large float32 sum buckets into
    quantized wire payloads (``"bf16"`` or ``"int8"``); ``error_budget``
    caps the declared relative error a compressed bucket may introduce
    (buckets whose bound exceeds it stay exact).  ``"none"`` — the default —
    keeps every sync bit-identical to the uncompressed planner.
    """

    every_n_steps: Optional[int] = None
    at_compute: bool = False
    compression: str = "none"
    error_budget: Optional[float] = None

    def __post_init__(self) -> None:
        # validates the mode/budget combination (raises ValueError on misuse)
        CompressionConfig.from_mode(self.compression, self.error_budget)
        if self.at_compute:
            if self.every_n_steps is not None:
                raise ValueError(
                    "SyncPolicy: pass either every_n_steps=k or at_compute=True, not both"
                )
        else:
            k = 1 if self.every_n_steps is None else self.every_n_steps
            if not (isinstance(k, int) and not isinstance(k, bool) and k >= 1):
                raise ValueError(
                    f"SyncPolicy.every_n_steps must be an int >= 1, got {self.every_n_steps!r}"
                )
            object.__setattr__(self, "every_n_steps", k)

    @property
    def defers(self) -> bool:
        """True when some steps run collective-free."""
        return self.at_compute or self.every_n_steps > 1

    @property
    def compression_config(self) -> Optional[CompressionConfig]:
        """``None`` for exact syncs, else the planner-facing config."""
        return CompressionConfig.from_mode(self.compression, self.error_budget)

    def should_sync(self, pending: int) -> bool:
        return (not self.at_compute) and pending >= self.every_n_steps

    @classmethod
    def every_n(cls, k: int) -> "SyncPolicy":
        """``SyncPolicy(every_n_steps=k)`` — the spelling :class:`SyncAdvisor`
        recommendations use."""
        return cls(every_n_steps=k)


class SyncStepper:
    """Cadence-controlled sharded accumulation for a metric or collection.

    Keeps one running state *per device* (a leading-axis-stacked, sharded
    carry), folds each step's shards in with a collective-free compiled step,
    and runs the coalesced bucketed sync only when the :class:`SyncPolicy`
    says so (or at :meth:`compute`).  The synced windows merge into a
    replicated cumulative state via the metric's own ``merge_states``.

    Interops with resilience: :meth:`snapshot`/:meth:`restore` capture BOTH
    the replicated cumulative state and the deferred per-device carry
    mid-window, and ``verify_consistency=True`` runs
    ``verify_replica_consistency`` on every synced window.

    Example::

        stepper = SyncStepper(collection, mesh=mesh, policy=SyncPolicy(every_n_steps=4))
        for batch in loader:
            stepper.update(*batch)      # collective only on every 4th step
        results = stepper.compute()     # flushes the open window
    """

    _SNAP_VERSION = 1

    def __init__(
        self,
        target: Any,
        mesh: Optional[Any] = None,
        axis_name: str = "data",
        policy: Optional[SyncPolicy] = None,
        verify_consistency: bool = False,
        in_specs: Optional[Any] = None,
        on_divergence: str = "raise",
    ) -> None:
        from torchmetrics_tpu.parallel.sync import metric_mesh

        if on_divergence not in ("raise", "quarantine"):
            raise ValueError(
                f'on_divergence must be "raise" or "quarantine", got {on_divergence!r}'
            )
        self.target = target
        self.mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
        self.axis_name = axis_name
        self.policy = policy if policy is not None else SyncPolicy()
        self.verify_consistency = verify_consistency
        self.in_specs = in_specs
        self.on_divergence = on_divergence
        self._is_collection = hasattr(target, "_functional_groups")
        if self._is_collection:
            names = tuple(members[0] for members in target._functional_groups().values())
            self._members: Tuple[Tuple[str, Any], ...] = tuple((n, target[n]) for n in names)
        else:
            self._members = (("", target),)
        listy = [n or type(m).__name__ for n, m in self._members if m._has_list_states]
        if listy:
            raise ValueError(
                f"SyncStepper accumulates fixed-size (psum-family) states in a compiled "
                f"carry; {listy} hold list (cat) states. Use DeferredRaggedSync for those — "
                "it already defers the gather to compute."
            )
        self._local: Optional[Dict[str, State]] = None  # {name: stacked sharded state}
        self._synced: Optional[Dict[str, State]] = None  # {name: replicated state}
        self._steps = 0
        self._pending = 0

    # ------------------------------------------------------------- properties
    @property
    def steps(self) -> int:
        """Total update steps folded in so far."""
        return self._steps

    @property
    def pending(self) -> int:
        """Steps accumulated locally since the last collective."""
        return self._pending

    # ------------------------------------------------------------------ carry
    def _n_devices(self) -> int:
        return int(self.mesh.devices.size)

    def _init_carry(self) -> Dict[str, State]:
        from jax.sharding import NamedSharding, PartitionSpec

        n = self._n_devices()
        sharding = NamedSharding(self.mesh, PartitionSpec(self.axis_name))
        carry: Dict[str, State] = {}
        for name, m in self._members:
            init = m.init_state()
            carry[name] = jax.tree.map(
                lambda x: jax.device_put(jnp.broadcast_to(x[None], (n, *x.shape)), sharding),
                init,
            )
        return carry

    def _unwrap(self, per_name: Dict[str, Any]) -> Any:
        return per_name if self._is_collection else per_name[""]

    # ------------------------------------------------------------------ steps
    def update(self, *inputs: Any) -> Optional[Any]:
        """Fold one sharded batch in.  Returns the cumulative replicated
        state(s) on sync steps, ``None`` on deferred (collective-free) ones."""
        from torchmetrics_tpu.core.compile import compiled_cadence_step

        fn = compiled_cadence_step(
            self.target, self._members, self.mesh, self.axis_name, self.in_specs, inputs
        )
        if self._local is None:
            self._local = self._init_carry()
        self._local = fn(self._local, *inputs)
        self._steps += 1
        self._pending += 1
        if self.policy.should_sync(self._pending):
            return self.sync()
        return None

    def _dispatch_window(self, comp: Optional[CompressionConfig]) -> Dict[str, State]:
        """One coalesced collective over the open carry — masked (weighted by
        the quarantine mask) whenever the target runs degraded."""
        from torchmetrics_tpu.core.compile import compiled_cadence_sync
        from torchmetrics_tpu.observability import registry as _telemetry
        from torchmetrics_tpu.resilience.quarantine import is_degraded, quarantine_mask

        degraded = is_degraded(self.target)
        fn = compiled_cadence_sync(
            self.target,
            self._members,
            self.mesh,
            self.axis_name,
            compression=comp,
            masked=degraded,
        )
        measuring = _telemetry.enabled()
        t0 = time.perf_counter() if measuring else 0.0  # tmt: ignore[TMT006] -- measured sync cost at the host boundary; outside any traced graph
        with _telemetry.span(self.target, "sync"):
            if degraded:
                window = fn(self._local, quarantine_mask(self.target, self.mesh, self.axis_name))
            else:
                window = fn(self._local)
            if measuring:
                # block so the span/measurement covers the collective
                # itself, not just its async dispatch
                jax.block_until_ready(window)
        n_dev = self._n_devices()
        for name, m in self._members:
            _telemetry.record_sync(m, m._reductions, window[name], n_dev, compression=comp)
        if measuring:
            measured_s = time.perf_counter() - t0  # tmt: ignore[TMT006] -- measured sync cost at the host boundary; outside any traced graph
            _telemetry.record_measured_sync(
                self.target,
                [(m._reductions, window[name]) for name, m in self._members],
                n_dev,
                measured_s,
                compression=comp,
            )
            # same window, process-wide: the fleet plane's straggler
            # attribution compares this digest across hosts
            _telemetry.record_sync_wait(measured_s)
        return window

    def _verify_window(self, window: Dict[str, State]) -> None:
        from torchmetrics_tpu.resilience.divergence import verify_replica_consistency

        for name, m in self._members:
            verify_replica_consistency(
                m, mesh=self.mesh, state=window[name], axis_name=self.axis_name
            )

    def sync(self) -> Any:
        """Flush the open window (if any) with one coalesced collective and
        return the cumulative replicated state(s).

        With ``verify_consistency=True`` and ``on_divergence="quarantine"``,
        a window whose replicas diverged is re-synced through the masked
        graph with the divergent replicas quarantined — the window's
        contribution comes from the surviving quorum (the quarantined
        devices' not-yet-synced carry is excluded, never silently summed).
        """
        comp = self.policy.compression_config
        if self._local is not None:
            window = self._dispatch_window(comp)
            if self.verify_consistency:
                from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

                try:
                    self._verify_window(window)
                except ReplicaDivergenceError as err:
                    from torchmetrics_tpu.parallel.sync import _quarantine_and_redispatch

                    window = _quarantine_and_redispatch(
                        self.target,
                        err,
                        self.on_divergence,
                        self.mesh,
                        self.axis_name,
                        lambda: self._dispatch_window(comp),
                        verify=lambda w: self._verify_window(w),
                    )
            if self._synced is None:
                self._synced = window
            else:
                self._synced = {
                    name: m.merge_states(self._synced[name], window[name])
                    for name, m in self._members
                }
            self._local = None
            self._pending = 0
        if self._synced is None:
            raise RuntimeError("SyncStepper.sync called before any update")
        return self._unwrap(self._synced)

    def compute(self) -> Any:
        """Flush pending steps, then compute from the cumulative state(s)."""
        synced = self.sync()
        if not self._is_collection:
            return self.target.compute_state(synced)
        return self.target.compute_states(synced)

    def reset(self) -> None:
        self._local = None
        self._synced = None
        self._steps = 0
        self._pending = 0

    # ------------------------------------------------------------- resilience
    def snapshot(self) -> Dict[str, Any]:
        """Host-portable capture of cumulative + deferred-local state —
        taking it mid-window preserves the not-yet-synced steps.

        ``n_devices`` records the producing mesh so a restore onto a
        different mesh fails with a mesh-shape diagnostic (and so
        ``resilience.elastic.elastic_restore`` can re-bucket the stacked
        carry) instead of surfacing as a bare leading-dim mismatch.
        """
        to_np = lambda tree: None if tree is None else jax.tree.map(np.asarray, tree)
        return {
            "version": self._SNAP_VERSION,
            "steps": self._steps,
            "pending": self._pending,
            "n_devices": self._n_devices(),
            "synced": to_np(self._synced),
            "local": to_np(self._local),
        }

    def restore(self, snap: Mapping[str, Any]) -> None:
        """Validate-then-install the counterpart of :meth:`snapshot`."""
        from jax.sharding import NamedSharding, PartitionSpec

        from torchmetrics_tpu.utilities.exceptions import StateRestoreError

        if not isinstance(snap, Mapping) or snap.get("version") != self._SNAP_VERSION:
            raise StateRestoreError(
                f"not a SyncStepper snapshot (version {self._SNAP_VERSION}): "
                f"got {type(snap).__name__} with version {getattr(snap, 'get', lambda *_: None)('version')}"
            )
        n = self._n_devices()
        names = [name for name, _ in self._members]
        snap_n = snap.get("n_devices")  # absent on pre-elastic (early v1) snapshots

        def check_tree(kind: str, tree: Any, stacked: bool) -> None:
            if tree is None:
                return
            if sorted(tree) != sorted(names):
                raise StateRestoreError(
                    f"snapshot {kind} states name {sorted(tree)}, stepper expects {sorted(names)}"
                )
            for name, m in self._members:
                ref = m.init_state()
                for leaf, default in ref.items():
                    if leaf not in tree[name]:
                        raise StateRestoreError(f"snapshot {kind}[{name!r}] is missing leaf {leaf!r}")
                    arr = np.asarray(tree[name][leaf])
                    want = (n, *default.shape) if stacked else tuple(default.shape)
                    if tuple(arr.shape) != want or arr.dtype != np.dtype(default.dtype):
                        if (
                            stacked
                            and arr.dtype == np.dtype(default.dtype)
                            and tuple(arr.shape[1:]) == tuple(default.shape)
                            and arr.shape[0] != n
                        ):
                            # leading-dim-only mismatch: a carry from a
                            # different mesh, not corruption
                            produced = int(arr.shape[0]) if snap_n is None else int(snap_n)
                            raise StateRestoreError(
                                f"snapshot {kind}[{name!r}][{leaf!r}] carries per-device state "
                                f"for a {produced}-device mesh, but this stepper runs on "
                                f"{n} devices. Use resilience.elastic.elastic_restore to "
                                "re-bucket the carry across the new mesh.",
                                leaf=leaf,
                                reason="mesh-shape",
                                mesh_shape=(produced,),
                            )
                        raise StateRestoreError(
                            f"snapshot {kind}[{name!r}][{leaf!r}] has shape {arr.shape}/"
                            f"{arr.dtype}, expected {want}/{np.dtype(default.dtype)}"
                        )

        check_tree("synced", snap.get("synced"), stacked=False)
        check_tree("local", snap.get("local"), stacked=True)
        synced = snap.get("synced")
        local = snap.get("local")
        self._synced = None if synced is None else jax.tree.map(jnp.asarray, dict(synced))
        if local is None:
            self._local = None
        else:
            sharding = NamedSharding(self.mesh, PartitionSpec(self.axis_name))
            self._local = jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x), sharding), dict(local)
            )
        self._steps = int(snap["steps"])
        self._pending = int(snap["pending"])


# -------------------------------------------------- sharded_update cadence glue
def cadence_stepper(
    target: Any,
    mesh: Any,
    axis_name: str,
    policy: SyncPolicy,
    verify_consistency: bool = False,
    in_specs: Optional[Any] = None,
    on_divergence: str = "raise",
) -> SyncStepper:
    """The implicit per-object :class:`SyncStepper` behind
    ``sharded_update(..., sync_policy=...)``.

    Cached on the target (``__dict__`` only — underscore-private, so it never
    perturbs config fingerprints and is dropped on pickling).  The cadence
    arguments must stay stable across steps: state already accumulated under
    one policy/mesh cannot be reinterpreted under another.
    """
    stepper: Optional[SyncStepper] = target.__dict__.get("_cadence_stepper")
    if stepper is not None:
        if (
            stepper.mesh is not mesh
            or stepper.axis_name != axis_name
            or stepper.policy != policy
            or stepper.verify_consistency != verify_consistency
            or stepper.on_divergence != on_divergence
        ):
            raise ValueError(
                "sync_policy cadence arguments changed mid-accumulation "
                f"(policy {stepper.policy} -> {policy}); call flush_sync(...) and reset, "
                "or drive a SyncStepper explicitly for dynamic cadences"
            )
        return stepper
    stepper = SyncStepper(
        target,
        mesh=mesh,
        axis_name=axis_name,
        policy=policy,
        verify_consistency=verify_consistency,
        in_specs=in_specs,
        on_divergence=on_divergence,
    )
    target.__dict__["_cadence_stepper"] = stepper
    return stepper


def flush_sync(target: Any) -> Any:
    """Force the pending deferred steps of ``sharded_update(...,
    sync_policy=...)`` / ``sharded_collection_update`` through their
    collective and return the cumulative replicated state(s)."""
    stepper: Optional[SyncStepper] = target.__dict__.get("_cadence_stepper")
    if stepper is None:
        raise RuntimeError(
            f"{type(target).__name__} has no pending cadence state — pass sync_policy= to "
            "sharded_update/sharded_collection_update first (or drive a SyncStepper directly)"
        )
    return stepper.sync()


# -------------------------------------------------------------------- advisor
class SyncAdvisor:
    """Report-only sync-cadence advisor driven by *measured* sync cost.

    The byte models above predict what a cadence change should save; this
    class measures it.  :meth:`profile` dry-runs the target under each
    candidate ``every_n`` cadence on the given mesh with telemetry on, so
    every flushed window is block-until-ready timed at the host boundary
    (``SyncStepper.sync``), then :meth:`recommend` names the smallest cadence
    whose measured sync-time cut clears ``target_cut`` — smallest because a
    longer window buys diminishing sync savings at growing staleness.

    Nothing here mutates the target's policy: the recommendation is a dict
    the caller applies (or ignores) via
    ``sharded_update(..., sync_policy=SyncPolicy.every_n(k))``.

    Example (8-device dryrun — the BENCH_r05 scenario)::

        advisor = SyncAdvisor(metric, mesh=mesh, axis_name="data")
        advisor.profile(preds, target, steps=16)
        rec = advisor.recommend()
        rec["every_n"]            # 4 on the 8-device CPU mesh
        rec["measured_cut"]       # ~4-5x less sync wall time than every-step
        rec["buckets"]            # per-bucket measured vs model bytes + residual
        rec["compression"]        # modelled byte cut per mode + recommended mode
    """

    def __init__(
        self,
        target: Any,
        mesh: Optional[Any] = None,
        axis_name: str = "data",
        in_specs: Optional[Any] = None,
        candidates: Sequence[int] = (1, 2, 4, 8),
        max_staleness: int = 8,
        compression: str = "none",
        error_budget: Optional[float] = None,
    ) -> None:
        from torchmetrics_tpu.parallel.sync import metric_mesh

        if 1 not in candidates:
            raise ValueError("SyncAdvisor candidates must include 1 (the measured baseline)")
        # validates the profiling mode (raises ValueError on misuse); unlike a
        # SyncPolicy, a budget WITHOUT a mode is meaningful here — it declares
        # the tolerance the compression *advice* is judged against while the
        # profile itself runs uncompressed (the autotuner's observe flow)
        CompressionConfig.from_mode(
            compression, error_budget if compression != "none" else None
        )
        self.target = target
        self.mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
        self.axis_name = axis_name
        self.in_specs = in_specs
        self.candidates = tuple(sorted(set(int(n) for n in candidates)))
        self.max_staleness = int(max_staleness)
        self.compression = compression
        self.error_budget = error_budget
        self._profile: Optional[Dict[str, Any]] = None

    def _member_metrics(self) -> List[Any]:
        if hasattr(self.target, "_functional_groups"):
            names = tuple(ms[0] for ms in self.target._functional_groups().values())
            return [self.target[n] for n in names]
        return [self.target]

    def _sync_byte_totals(self) -> Dict[str, int]:
        """Summed ``sync_bytes``/``sync_bytes_raw`` counters across the
        profiled metric(s) — the measured per-cadence byte surface."""
        from torchmetrics_tpu.observability import registry as _telemetry

        out = {"sync_bytes": 0, "sync_bytes_raw": 0}
        for m in self._member_metrics():
            counters = _telemetry.telemetry_for(m).as_dict()["counters"]
            for key in out:
                out[key] += int(counters.get(key, 0))
        return out

    def profile(self, *inputs: Any, steps: int = 16, rounds: int = 3) -> Dict[str, Any]:
        """Measure total sync wall time over ``steps`` updates of ``inputs``
        under each candidate cadence (telemetry is enabled for the dryrun and
        restored after).

        An untimed warmup window runs first so no candidate's measurement
        pays the cadence step/sync compile; candidates are then measured
        ``rounds`` times round-robin and each keeps its *minimum* total —
        the standard noise-robust wall-clock estimator, so one scheduler
        hiccup cannot flip the recommendation.
        """
        from torchmetrics_tpu.observability import registry as _telemetry

        was_enabled = _telemetry.enabled()
        if not was_enabled:
            _telemetry.enable()
        cands = [n for n in self.candidates if n <= steps and n <= self.max_staleness]
        if 1 not in cands:
            # the every-step baseline every recommendation is judged against:
            # always measured, even when steps/max_staleness exclude it above
            cands.insert(0, 1)
        totals: Dict[int, List[Dict[str, float]]] = {n: [] for n in cands}
        bytes_by_cand: Dict[int, Dict[str, int]] = {}
        policy_of = lambda n: SyncPolicy(
            every_n_steps=n,
            compression=self.compression,
            # an advice-only budget (compression "none") never reaches the
            # measured policies — the profile runs exact
            error_budget=self.error_budget if self.compression != "none" else None,
        )
        before_all = _telemetry.telemetry_for(self.target).as_dict()
        try:
            warm = SyncStepper(
                self.target,
                mesh=self.mesh,
                axis_name=self.axis_name,
                policy=policy_of(1),
                in_specs=self.in_specs,
            )
            warm.update(*inputs)  # compiles the cadence step + sync untimed
            for _ in range(max(int(rounds), 1)):
                for n in cands:
                    stepper = SyncStepper(
                        self.target,
                        mesh=self.mesh,
                        axis_name=self.axis_name,
                        policy=policy_of(n),
                        in_specs=self.in_specs,
                    )
                    before = _telemetry.telemetry_for(self.target).as_dict()
                    bytes_before = self._sync_byte_totals()
                    for _ in range(steps):
                        stepper.update(*inputs)
                    if stepper.pending:
                        stepper.sync()
                    after = _telemetry.telemetry_for(self.target).as_dict()
                    totals[n].append(_span_delta(after, before, "sync"))
                    bytes_after = self._sync_byte_totals()
                    # deterministic per cadence — identical every round
                    bytes_by_cand[n] = {
                        key: bytes_after[key] - bytes_before[key] for key in bytes_after
                    }
            after_all = _telemetry.telemetry_for(self.target).as_dict()
        finally:
            if not was_enabled:
                _telemetry.disable()
        runs: List[Dict[str, Any]] = []
        for n in cands:
            best = min(totals[n], key=lambda d: d["total_s"])
            nbytes = bytes_by_cand[n]
            runs.append(
                {
                    "every_n": n,
                    "steps": steps,
                    "rounds": len(totals[n]),
                    "syncs": best["count"],
                    "sync_s": best["total_s"],
                    "mean_sync_s": best["total_s"] / max(best["count"], 1),
                    "sync_wire_bytes": nbytes["sync_bytes"],
                    "sync_raw_bytes": nbytes["sync_bytes_raw"],
                    "mean_sync_bytes": nbytes["sync_bytes"] / max(best["count"], 1),
                }
            )
        self._profile = {
            "steps": steps,
            "n_devices": int(self.mesh.devices.size),
            "runs": runs,
            "buckets": _bucket_delta(after_all, before_all),
        }
        return self._profile

    def _compression_advice(self) -> Dict[str, Any]:
        """Modelled per-chip byte cut for each compression mode on the
        profiled metric(s)' sync plan, folded into the recommendation.

        Report-only like the cadence advice: the strongest mode whose
        declared error bound fits ``self.error_budget`` (and actually cuts
        bytes) is named ``recommended_mode``; with no budget declared the
        advice stays ``"none"`` — quantized syncs are an explicit opt-in.

        Measured evidence trumps the model: when the accuracy plane has
        recorded *observed* quantization error for a mode (shadow-exact
        audits / ``record_quant_error`` rows on the target's sync buckets),
        the mode's row carries the mean observed relative error, and a mode
        observed over budget is struck from ``recommended_mode`` eligibility
        even if its predicted bound fits.
        """
        from torchmetrics_tpu.observability import registry as _telemetry
        from torchmetrics_tpu.utilities.benchmark import coalesced_sync_bytes_per_chip

        n_dev = int(self.mesh.devices.size)
        members = self._member_metrics()

        # observed quantization error by mode, pooled over the target's (and
        # members') compressed sync buckets
        observed: Dict[str, List[float]] = {}
        pool = {id(obj): obj for obj in (self.target, *members)}
        for obj in pool.values():
            row = _telemetry.telemetry_for(obj).as_dict()
            for b in row.get("sync_buckets", {}).values():
                mode = b.get("compression")
                count = int(b.get("quant_err_count", 0))
                if mode in (None, "none") or not count:
                    continue
                observed.setdefault(str(mode), []).append(
                    float(b.get("quant_rel_err_sum", 0.0)) / count
                )

        def model_bytes(cfg: Optional[CompressionConfig]) -> int:
            total = 0
            for m in members:
                _, sub = _metric_entry(m, m._state)
                total += coalesced_sync_bytes_per_chip(
                    m._reductions, sub, n_dev, compression=cfg
                )
            return total

        exact = model_bytes(None)
        modes: Dict[str, Dict[str, Any]] = {}
        for mode in ("bf16", "int8"):
            cfg = CompressionConfig(mode=mode, error_budget=self.error_budget)
            wire = model_bytes(cfg)
            bound = predicted_error_bound(mode, stages=2 if mode == "int8" else 1)
            row = {
                "model_wire_bytes": wire,
                "model_byte_cut": exact / max(wire, 1),
                "error_bound": bound,
                "within_budget": self.error_budget is not None and bound <= self.error_budget,
            }
            if mode in observed:
                samples = observed[mode]
                row["observed_rel_err"] = sum(samples) / len(samples)
                row["observed_samples"] = len(samples)
                row["observed_within_budget"] = (
                    self.error_budget is not None
                    and row["observed_rel_err"] <= self.error_budget
                )
            modes[mode] = row
        recommended = "none"
        if self.error_budget is not None:
            eligible = [
                (row["model_byte_cut"], mode)
                for mode, row in modes.items()
                if row["within_budget"]
                and row["model_byte_cut"] > 1.0
                # measured over-budget error disqualifies regardless of model
                and row.get("observed_within_budget", True)
            ]
            if eligible:
                recommended = max(eligible)[1]
        return {
            "mode": self.compression,
            "error_budget": self.error_budget,
            "recommended_mode": recommended,
            "model_exact_bytes": exact,
            "modes": modes,
        }

    def recommend(self, target_cut: float = 3.5, fleet: Optional[Any] = None) -> Dict[str, Any]:
        """The smallest profiled cadence whose measured sync-time cut (vs the
        every-step baseline) reaches ``target_cut`` — or the best-measured
        cadence when none does.  Report-only.

        ``fleet`` folds cross-host context into the advice: pass an
        ``observability.fleet.FleetView`` (or its ``skew()`` dict) and the
        recommendation gains a ``"fleet"`` block naming the straggler process
        and its wait ratio — when one host dominates the measured sync wait,
        cadence/compression tuning is the wrong lever and the note says so.
        """
        if self._profile is None:
            raise RuntimeError("SyncAdvisor.recommend called before profile()")
        runs = self._profile["runs"]
        base = next((r for r in runs if r["every_n"] == 1), None)
        if base is None:
            # profile() always measures cadence 1, so this only fires on a
            # hand-built/deserialized profile missing the baseline
            raise RuntimeError(
                "SyncAdvisor.recommend: the profile has no every_n == 1 baseline run "
                f"(measured cadences: {sorted(r.get('every_n') for r in runs)}); every "
                "measured_cut is relative to the every-step baseline — re-run profile(), "
                "or include an every_n == 1 row in the supplied profile"
            )
        base_s = max(base["sync_s"], 1e-9)
        for r in runs:
            r["measured_cut"] = base_s / max(r["sync_s"], 1e-9)
        eligible = [r for r in runs if r["measured_cut"] >= target_cut]
        best = min(eligible, key=lambda r: r["every_n"]) if eligible else max(
            runs, key=lambda r: r["measured_cut"]
        )
        buckets = self._profile["buckets"]
        granule_bound = sorted(
            key
            for key, row in buckets.items()
            if row.get("model_naive_bytes", 0)
            and row.get("model_ring_bytes", 0) >= 2 * row["model_naive_bytes"]
        )
        out = {
            # export-front-door stamp: obs.export(rec, fmt="jsonl") lines are
            # filterable by kind and parse back via parse_export_line
            "kind": "sync_advice",
            "policy": "every_n",
            "every_n": best["every_n"],
            "measured_cut": best["measured_cut"],
            "target_cut": target_cut,
            "baseline_sync_s": base["sync_s"],
            "sync_s": best["sync_s"],
            "sync_wire_bytes": best["sync_wire_bytes"],
            "sync_raw_bytes": best["sync_raw_bytes"],
            "runs": runs,
            "buckets": buckets,
            "compression": self._compression_advice(),
            # buckets whose ring-model bytes dwarf the naive prediction are
            # granule-floor-bound: deferral (fewer windows) is what pays there
            "granule_bound_buckets": granule_bound,
            "note": (
                "report-only: apply with sharded_update(..., "
                f"sync_policy=SyncPolicy.every_n({best['every_n']}))"
            ),
        }
        if fleet is not None:
            out["fleet"] = self._fleet_advice(fleet)
        return out

    @staticmethod
    def _fleet_advice(fleet: Any) -> Dict[str, Any]:
        """Cross-host context for the recommendation: straggler process and
        wait skew out of an ``observability.fleet.FleetView`` (or an
        already-built ``skew()`` mapping)."""
        skew = fleet.skew() if hasattr(fleet, "skew") else dict(fleet)
        straggler = skew.get("straggler", {})
        ratio = float(straggler.get("vs_median", 1.0))
        advice = {
            "n_processes": skew.get("n_processes"),
            "straggler": straggler.get("process"),
            "wait_skew_ratio": ratio,
            "sync_wait_us": skew.get("sync_wait_us"),
        }
        if ratio >= 2.0:
            advice["note"] = (
                f"process {straggler.get('process')} waits {ratio:.1f}x the fleet "
                "median in collectives — investigate that host (data feed, thermal "
                "throttle, neighbor load) before retuning cadence: a straggler "
                "dominates every cadence equally"
            )
            # cross-reference the memory plane: a straggler that also tops the
            # live-HBM axis is likely paging/allocator-bound, not feed-bound
            hbm = skew.get("hbm_bytes")
            if isinstance(hbm, Mapping):
                hbm_ratio = float(hbm.get("skew_ratio", 1.0))
                if hbm.get("max_process") == straggler.get("process") and hbm_ratio >= 2.0:
                    advice["footprint_note"] = (
                        f"the straggler also holds {hbm_ratio:.1f}x the fleet-median "
                        "live metric-state HBM — check its resident footprint "
                        "(memory_report / tm_tpu_memory_state_bytes) before blaming "
                        "the interconnect"
                    )
        else:
            advice["note"] = (
                "sync wait is balanced across processes; cadence/compression "
                "tuning applies fleet-wide"
            )
        return advice


def _span_delta(
    after: Mapping[str, Any], before: Mapping[str, Any], name: str
) -> Dict[str, float]:
    a = after.get("spans", {}).get(name, {})
    b = before.get("spans", {}).get(name, {})
    return {
        "count": int(a.get("count", 0)) - int(b.get("count", 0)),
        "total_s": (float(a.get("total_us", 0.0)) - float(b.get("total_us", 0.0))) / 1e6,
    }


def _bucket_delta(
    after: Mapping[str, Any], before: Mapping[str, Any]
) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for key, row in after.get("sync_buckets", {}).items():
        prev = before.get("sync_buckets", {}).get(key, {})
        out[key] = {
            f: (v - prev.get(f, 0)) if isinstance(v, (int, float)) else v
            for f, v in row.items()
        }
    return out
