"""Compressed collectives: opt-in quantization for bucketed state syncs.

The coalescing planner (``parallel/coalesce.py``) flattens metric states into a
handful of dtype/op buckets and issues one collective per bucket.  At pod scale
the remaining cost is the *bytes* those collectives move — per EQuARX-style
quantized all-reduce, shrinking the wire payload 2-4x is worth far more than
shaving another launch.  This module supplies the compression stage the planner
can attach to individual buckets:

``bf16``
    Cast the fp32 bucket to bfloat16, run a single ``psum``, cast back.  One
    collective, exactly half the bytes, ~2**-8 relative error.  The compiler
    fuses both casts into the surrounding trace, so the compiled artifact is
    still one fused sync program.

``int8``
    Two-phase quantized all-reduce with per-chunk symmetric scales computed
    in-graph.  Each device splits the bucket into ``n_devices`` equal blocks,
    quantizes every block to int8 with one fp32 scale per ``chunk`` elements,
    and exchanges blocks with ``all_to_all`` — so device *k* receives all
    senders' copies of block *k*.  It dequantizes, sums its block exactly in
    fp32, requantizes the partial, and an ``all_gather`` of the packed payloads
    completes the allreduce.  Two collectives per bucket, ~4x fewer bytes than
    the fp32 ring, with error bounded by two quantization stages of 1/127 of
    the per-chunk max magnitude each.

Both paths are pure ``jax.lax`` graphs: no host callbacks, no extra compile
cache entries (the compression config rides the existing cache key only when
active), and they trace fine under ``shard_map(check_vma=False)`` like every
other sync in this library.

Exactness contract: the planner only ever attaches compression to *float32
sum* buckets at or above ``min_bucket_bytes``.  Integer buckets (Accuracy-style
correct/total counts), min/max buckets, and passthrough leaves are never
compressed, so count-based metrics remain bit-exact even with compression
enabled.  Host/DCN process-group syncs (``coalesced_host_sync``) can compress
with a single quantization stage; the two-stage DCN *model* in
``utilities/benchmark.py`` prices both topologies.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "COMPRESSION_MODES",
    "CompressionConfig",
    "CompressionSpec",
    "DEFAULT_CHUNK",
    "DEFAULT_MIN_BUCKET_BYTES",
    "PREDICTED_EXACT_INT_LIMIT",
    "PREDICTED_REL_ERROR",
    "predicted_error_bound",
    "predicted_exact_int_limit",
    "SCALE_BYTES",
    "bucket_wire_bytes",
    "compressed_psum",
    "compression_bound_provenance",
    "compression_spec_for",
    "host_compressed_payload_bytes",
    "host_dequantize_int8",
    "host_quantize_int8",
    "int8_block_bytes",
    "psum_bf16",
    "psum_int8",
]

# Quantization granularity: one fp32 scale per CHUNK int8 payload elements.
DEFAULT_CHUNK = 256
# Buckets below this byte size are never compressed: the fixed per-chunk scale
# overhead (and the all_to_all block padding) erases the win on small payloads.
DEFAULT_MIN_BUCKET_BYTES = 4096
SCALE_BYTES = 4  # one fp32 scale per chunk rides the packed payload

COMPRESSION_MODES = ("none", "bf16", "int8")

# Declared per-stage relative error bound (w.r.t. the per-chunk max magnitude).
# bf16 keeps 8 mantissa bits; symmetric int8 rounds to 1/127 of the chunk amax.
# The device int8 path quantizes twice (sender blocks, then the requantized
# partial sum), so its end-to-end bound is 2x the per-stage figure.
PREDICTED_REL_ERROR: Dict[str, float] = {
    "bf16": 2.0 ** -8,
    "int8": 2.0 / 127.0,
}


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """Policy-level compression request, hashable so it can ride cache keys.

    ``mode`` is ``"bf16"`` or ``"int8"`` (``"none"`` never reaches a config —
    callers pass ``None`` instead, keeping default cache keys byte-identical).
    ``error_budget`` is an optional relative-error ceiling: buckets whose
    declared bound exceeds it stay exact.  ``min_bucket_bytes`` is the size
    floor below which buckets stay exact regardless of mode.
    """

    mode: str
    error_budget: Optional[float] = None
    min_bucket_bytes: int = DEFAULT_MIN_BUCKET_BYTES
    chunk: int = DEFAULT_CHUNK

    def __post_init__(self) -> None:
        if self.mode not in ("bf16", "int8"):
            raise ValueError(
                f"compression mode must be 'bf16' or 'int8', got {self.mode!r}"
                " (use compression=None / 'none' for exact syncs)"
            )
        if self.error_budget is not None and not self.error_budget > 0:
            raise ValueError(f"error_budget must be positive, got {self.error_budget!r}")
        if self.min_bucket_bytes < 0:
            raise ValueError(f"min_bucket_bytes must be >= 0, got {self.min_bucket_bytes!r}")
        if self.chunk < 8:
            raise ValueError(f"chunk must be >= 8, got {self.chunk!r}")

    @classmethod
    def from_mode(
        cls, mode: Optional[str], error_budget: Optional[float] = None
    ) -> Optional["CompressionConfig"]:
        """``"none"``/``None`` -> ``None``; otherwise a validated config."""
        if mode is None or mode == "none":
            if error_budget is not None:
                raise ValueError("error_budget requires compression='bf16' or 'int8'")
            return None
        return cls(mode=mode, error_budget=error_budget)


@dataclasses.dataclass(frozen=True)
class CompressionSpec:
    """Per-bucket compression decision recorded in the ``SyncPlan``.

    ``error_bound`` is the declared end-to-end relative error bound for this
    bucket (w.r.t. per-chunk max magnitude); plan tests compare it against the
    policy's ``error_budget``.
    """

    mode: str
    chunk: int = DEFAULT_CHUNK
    error_bound: float = 0.0

    @property
    def n_collectives(self) -> int:
        """Collectives this compressed bucket issues (int8 is two-phase)."""
        return 2 if self.mode == "int8" else 1


def predicted_error_bound(mode: str, *, stages: int = 1) -> float:
    """Declared relative error bound for ``mode`` across ``stages`` stages."""
    return PREDICTED_REL_ERROR[mode] * stages


def compression_bound_provenance(
    mode: str, *, budget: Optional[float] = None
) -> Dict[str, object]:
    """One accuracy-plane provenance source for a committed compression mode:
    the predicted end-to-end bound plus how it was derived (this module stays
    the single authority on quantization bounds — the attestation plane in
    ``observability/accuracy.py`` composes these rows, it never re-derives
    them).  The device int8 path quantizes twice, so its bound is two stages.
    """
    stages = 2 if mode == "int8" else 1
    return {
        "source": "compression",
        "mode": mode,
        "stages": stages,
        "bound": predicted_error_bound(mode, stages=stages),
        "budget": budget,
    }


# Largest integer count a compressed wire format carries *exactly*.  bf16's
# 8 mantissa bits represent every integer up to 2**8; symmetric int8 scales
# by amax/127, so integers survive only in degenerate cases — declared 0.
# The static numerics pass (analysis/numerics.py, TMT015) uses this to
# reject plans that route proven exact counters through a quantized bucket.
PREDICTED_EXACT_INT_LIMIT: Dict[str, float] = {
    "bf16": 2.0 ** 8,
    "int8": 0.0,
}


def predicted_exact_int_limit(mode: str) -> float:
    """Largest integer value ``mode`` round-trips exactly (0 = none)."""
    return PREDICTED_EXACT_INT_LIMIT[mode]


def compression_spec_for(
    dtype: str, op: str, nbytes: int, config: Optional[CompressionConfig]
) -> Optional[CompressionSpec]:
    """Decide whether a planner bucket may be compressed.

    Only float32 *sum* buckets (MEAN leaves ride sum buckets and divide after
    the reduce, so they qualify too) at or above the byte floor are eligible;
    integer, min/max and small buckets always stay exact.  Returns ``None``
    when the bucket must remain exact.
    """
    if config is None:
        return None
    if op != "sum" or dtype != "float32":
        return None
    if nbytes < config.min_bucket_bytes:
        return None
    # The device int8 path quantizes twice: sender blocks + requantized partial.
    stages = 2 if config.mode == "int8" else 1
    bound = predicted_error_bound(config.mode, stages=stages)
    if config.error_budget is not None and bound > config.error_budget:
        return None
    return CompressionSpec(mode=config.mode, chunk=config.chunk, error_bound=bound)


# ---------------------------------------------------------------------------
# In-graph quantized collectives
# ---------------------------------------------------------------------------


def _quantize_chunks(x: jnp.ndarray, n_chunks: int, chunk: int) -> jnp.ndarray:
    """Pack ``(n_chunks * chunk,)`` fp32 into uint8 ``[int8 payload | fp32 scales]``."""
    xc = x.reshape(n_chunks, chunk)
    amax = jnp.max(jnp.abs(xc), axis=1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xc / scale[:, None]), -127, 127).astype(jnp.int8)
    q_bytes = jax.lax.bitcast_convert_type(q, jnp.uint8).reshape(-1)
    scale_bytes = jax.lax.bitcast_convert_type(scale, jnp.uint8).reshape(-1)
    return jnp.concatenate([q_bytes, scale_bytes])


def _dequantize_chunks(packed: jnp.ndarray, n_chunks: int, chunk: int) -> jnp.ndarray:
    """Inverse of :func:`_quantize_chunks`; returns ``(n_chunks * chunk,)`` fp32."""
    q_bytes = packed[: n_chunks * chunk].reshape(n_chunks, chunk)
    q = jax.lax.bitcast_convert_type(q_bytes, jnp.int8)
    scale_bytes = packed[n_chunks * chunk :].reshape(n_chunks, SCALE_BYTES)
    scale = jax.lax.bitcast_convert_type(scale_bytes, jnp.float32)
    return (q.astype(jnp.float32) * scale[:, None]).reshape(-1)


def psum_bf16(flat: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """All-reduce ``flat`` over ``axis_name`` with a bfloat16 wire payload."""
    return jax.lax.psum(flat.astype(jnp.bfloat16), axis_name).astype(flat.dtype)


def psum_int8(flat: jnp.ndarray, axis_name: str, chunk: int = DEFAULT_CHUNK) -> jnp.ndarray:
    """Two-phase int8 all-reduce with per-chunk symmetric scales, in-graph.

    Phase 1: quantize ``n`` destination blocks locally, ``all_to_all`` so each
    device holds every sender's copy of one block.  Phase 2: dequantize, sum
    the block exactly in fp32, requantize, ``all_gather`` the packed partials.
    The whole exchange is two uint8 collectives inside the same fused trace —
    no host round-trips and no extra compile-cache entries.
    """
    orig_dtype = flat.dtype
    flat = flat.astype(jnp.float32)
    # Under shard_map the axis size constant-folds to a concrete Python int.
    n = jax.lax.psum(1, axis_name)
    size = flat.shape[0]
    n_chunks = -(-size // (n * chunk))  # chunks per destination block
    padded = n * n_chunks * chunk
    blocks = jnp.pad(flat, (0, padded - size)).reshape(n, n_chunks * chunk)
    packed = jnp.stack([_quantize_chunks(blocks[j], n_chunks, chunk) for j in range(n)])
    received = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    partial = jnp.stack(
        [_dequantize_chunks(received[k], n_chunks, chunk) for k in range(n)]
    ).sum(axis=0)
    repacked = _quantize_chunks(partial, n_chunks, chunk)
    gathered = jax.lax.all_gather(repacked, axis_name, axis=0, tiled=False)
    out = jnp.concatenate([_dequantize_chunks(gathered[k], n_chunks, chunk) for k in range(n)])
    return out[:size].astype(orig_dtype)


def compressed_psum(flat: jnp.ndarray, axis_name: str, spec: CompressionSpec) -> jnp.ndarray:
    """Dispatch a bucket all-reduce through the spec's compression mode."""
    if spec.mode == "bf16":
        return psum_bf16(flat, axis_name)
    if spec.mode == "int8":
        return psum_int8(flat, axis_name, spec.chunk)
    raise ValueError(f"unknown compression mode {spec.mode!r}")


def psum_scatter_bf16(mat: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Reduce-scatter ``(n, K) -> (K,)`` with a bfloat16 wire payload."""
    return jax.lax.psum_scatter(
        mat.astype(jnp.bfloat16), axis_name, scatter_dimension=0, tiled=False
    ).astype(mat.dtype)


def psum_scatter_int8(
    mat: jnp.ndarray, axis_name: str, chunk: int = DEFAULT_CHUNK
) -> jnp.ndarray:
    """Reduce-scatter leg of the int8 exchange: ``(n, K) -> (K,)``.

    Exactly :func:`psum_int8`'s phases 1-2 — quantize the ``n`` destination
    blocks locally, ``all_to_all`` so each device holds every sender's copy
    of its own block, dequantize and sum exactly in fp32 — with the
    replicating requantize+``all_gather`` phases dropped: a sharded bucket
    keeps the block resident per device, so the partial sum IS the result.
    One collective, one quantization stage (senders only; the sum itself is
    never requantized), ``(n-1)`` packed-block hops per chip instead of the
    all-reduce's ``2(n-1)``.
    """
    orig_dtype = mat.dtype
    mat = mat.astype(jnp.float32)
    n, k = int(mat.shape[0]), int(mat.shape[1])
    n_chunks = -(-k // chunk)
    blocks = jnp.pad(mat, ((0, 0), (0, n_chunks * chunk - k)))
    packed = jnp.stack([_quantize_chunks(blocks[j], n_chunks, chunk) for j in range(n)])
    received = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    partial = jnp.stack(
        [_dequantize_chunks(received[j], n_chunks, chunk) for j in range(n)]
    ).sum(axis=0)
    return partial[:k].astype(orig_dtype)


def compressed_psum_scatter(
    mat: jnp.ndarray, axis_name: str, spec: CompressionSpec
) -> jnp.ndarray:
    """Dispatch a sharded bucket's ``(n, K) -> (K,)`` reduce-scatter through
    the spec's compression mode."""
    if spec.mode == "bf16":
        return psum_scatter_bf16(mat, axis_name)
    if spec.mode == "int8":
        return psum_scatter_int8(mat, axis_name, spec.chunk)
    raise ValueError(f"unknown compression mode {spec.mode!r}")


# ---------------------------------------------------------------------------
# Wire-byte models (consumed by utilities/benchmark.py and telemetry)
# ---------------------------------------------------------------------------


def int8_block_bytes(size: int, n_devices: int, chunk: int = DEFAULT_CHUNK) -> int:
    """Packed bytes of one destination block in the int8 two-phase exchange."""
    n_chunks = -(-size // (n_devices * chunk))
    return n_chunks * chunk + SCALE_BYTES * n_chunks


def _granule_ceil(nbytes: int, granule: Optional[int]) -> int:
    if granule is None or granule <= 0:
        return nbytes
    return -(-nbytes // granule) * granule


def bucket_wire_bytes(
    size: int,
    itemsize: int,
    n_devices: int,
    spec: Optional[CompressionSpec],
    granule: Optional[int] = None,
    sharded: bool = False,
) -> int:
    """Modelled per-chip wire bytes for one bucket all-reduce.

    ``granule=None`` gives the naive (granule-free) model used by the
    ``sync_bytes`` telemetry counter; an integer granule gives the ring model
    matching ``utilities.benchmark.ring_reduce_bytes``.  Exact and bf16 buckets
    follow the ring schedule (2(n-1) payload-chunk hops per chip); the int8
    two-phase exchange moves 2(n-1) packed blocks per chip (n-1 in the
    ``all_to_all`` scatter phase, n-1 in the ``all_gather`` phase).

    ``sharded=True`` prices a reduce-scatter bucket: the replicating second
    half of the ring schedule (and the int8 ``all_gather`` phase) is
    dropped, so every mode moves exactly half the hops — ``(n-1)`` instead
    of ``2(n-1)`` — per chip.
    """
    n = int(n_devices)
    if n <= 1:
        return 0
    if spec is None or spec.mode == "none":
        payload = size * itemsize
    elif spec.mode == "bf16":
        payload = size * 2
    elif spec.mode == "int8":
        block = int8_block_bytes(size, n, spec.chunk)
        hops = (n - 1) if sharded else 2 * (n - 1)
        return hops * _granule_ceil(block, granule)
    else:
        raise ValueError(f"unknown compression mode {spec.mode!r}")
    hops = (n - 1) if sharded else 2 * (n - 1)
    if granule is None:
        return int(round(hops / n * payload))
    return hops * _granule_ceil(-(-payload // n), granule)


def host_compressed_payload_bytes(size: int, itemsize: int, spec: Optional[CompressionSpec]) -> int:
    """Per-process payload bytes a host/DCN gather ships for one bucket."""
    if spec is None or spec.mode == "none":
        return size * itemsize
    if spec.mode == "bf16":
        return size * 2
    if spec.mode == "int8":
        n_chunks = -(-size // spec.chunk)
        return size + SCALE_BYTES * n_chunks
    raise ValueError(f"unknown compression mode {spec.mode!r}")


# ---------------------------------------------------------------------------
# Host-path (process-group / DCN) quantization — single stage, numpy
# ---------------------------------------------------------------------------


def host_quantize_int8(flat: np.ndarray, chunk: int = DEFAULT_CHUNK) -> np.ndarray:
    """Pack an fp32 vector into the uint8 ``[int8 payload | fp32 scales]`` layout."""
    flat = np.asarray(flat, dtype=np.float32)
    size = flat.shape[0]
    n_chunks = -(-size // chunk)
    padded = np.pad(flat, (0, n_chunks * chunk - size)).reshape(n_chunks, chunk)
    amax = np.max(np.abs(padded), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(padded / scale[:, None]), -127, 127).astype(np.int8)
    return np.concatenate([q.view(np.uint8).reshape(-1), scale.view(np.uint8).reshape(-1)])


def host_dequantize_int8(
    packed: np.ndarray, size: int, chunk: int = DEFAULT_CHUNK
) -> np.ndarray:
    """Inverse of :func:`host_quantize_int8`, trimmed back to ``size`` elements."""
    packed = np.asarray(packed, dtype=np.uint8)
    n_chunks = -(-size // chunk)
    q = packed[: n_chunks * chunk].view(np.int8).reshape(n_chunks, chunk)
    scale = packed[n_chunks * chunk :].view(np.float32)
    return (q.astype(np.float32) * scale[:, None]).reshape(-1)[:size]


# ---------------------------------------------------------------------------
# Ragged bitpack width selection
# ---------------------------------------------------------------------------

_PACK_CANDIDATES: Tuple[np.dtype, ...] = (
    np.dtype(np.uint8),
    np.dtype(np.int8),
    np.dtype(np.uint16),
    np.dtype(np.int16),
    np.dtype(np.uint32),
    np.dtype(np.int32),
)


def packed_int_dtype(dtype: np.dtype, value_range: Tuple[float, float]) -> np.dtype:
    """Narrowest integer dtype that covers a declared ``(lo, hi)`` value range.

    Used to bitpack ragged CAT gathers: token ids declared in ``[0, 50k)``
    travel as uint16 instead of int32, detection labels in ``[0, 80]`` as
    uint8.  The width is static — it comes from ``add_state(value_range=...)``,
    not from the data — so the gather trace stays cache-stable.  Returns the
    original dtype when no narrowing applies (float dtypes, or ranges needing
    the full width).
    """
    dtype = np.dtype(dtype)
    if dtype.kind not in ("i", "u"):
        return dtype
    lo, hi = value_range
    if lo > hi:
        raise ValueError(f"value_range lo must be <= hi, got {value_range!r}")
    for cand in _PACK_CANDIDATES:
        if cand.itemsize >= dtype.itemsize:
            break
        info = np.iinfo(cand)
        if info.min <= lo and hi <= info.max:
            return cand
    return dtype
