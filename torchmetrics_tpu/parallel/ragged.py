"""Ragged (variable-length) list-state sync across a device mesh.

The hardest sync path in the reference is detection mAP's per-image
variable-length cat states: each rank holds a *different number* of
per-image tensors with *different shapes*, and its custom ``_sync_dist``
pads every tensor to the world max, all_gathers, and trims
(/root/reference/src/torchmetrics/detection/mean_ap.py:1022-1046 via
``gather_all_tensors``'s pad-gather-trim slow path,
/root/reference/src/torchmetrics/utilities/distributed.py:136-147).

The TPU-native equivalent here: per-device list states are packed into ONE
padded buffer + one per-item shape table per state name (items are padded in
*every* dimension to the mesh max, like the reference's all-dims pad), a
single tiled ``all_gather`` per state crosses the mesh inside ``shard_map``
(ICI — not one collective per tensor like the reference's per-tensor
gather), and the items are re-split on host.  Scalar (psum/pmax/...) states
ride the same shard_map call, so a metric mixing tensor and list states
syncs in one graph.

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.core.reductions import Reduce
    >>> from torchmetrics_tpu.parallel import metric_mesh, sync_ragged_states
    >>> mesh = metric_mesh()
    >>> n_dev = mesh.devices.size
    >>> # each device holds a DIFFERENT number of variable-length items
    >>> per_dev = [{"items": (jnp.full((d % 3 + 1,), float(d)),)} for d in range(n_dev)]
    >>> merged = sync_ragged_states({"items": Reduce.CAT}, per_dev, mesh)
    >>> len(merged["items"]) == n_dev  # every device's item arrived, in order
    True
    >>> [int(v.shape[0]) for v in merged["items"]] == [d % 3 + 1 for d in range(n_dev)]
    True
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu.core.compile import bucket_dim, compiled_ragged_gather
from torchmetrics_tpu.core.reductions import Reduce, sync_leaf
from torchmetrics_tpu.observability import registry as _telemetry

State = Dict[str, Any]
_N = "_n"
_NONFINITE = "_nonfinite"


def _pack_items(
    items: Sequence[Any], max_trailing: Tuple[int, ...], dtype
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a device's items to the global trailing dims and concatenate along
    the leading axis.  Returns (buffer, shapes) with shapes (k, ndim)."""
    ndim = 1 + len(max_trailing)
    shapes = np.zeros((len(items), ndim), np.int32)
    padded = []
    for j, it in enumerate(items):
        arr = np.asarray(it)
        if arr.ndim != ndim:
            raise ValueError(
                f"ragged list-state items must share rank: got {arr.ndim}d item among {ndim}d items"
            )
        shapes[j] = arr.shape
        pad = [(0, 0)] + [(0, m - s) for m, s in zip(max_trailing, arr.shape[1:])]
        padded.append(np.pad(arr, pad) if any(p != (0, 0) for p in pad) else arr)
    if padded:
        buf = np.concatenate(padded, axis=0)
    else:
        buf = np.zeros((0, *max_trailing), dtype)
    return buf.astype(dtype, copy=False), shapes


def _ragged_meta(per_device_items: Sequence[Sequence[Any]]) -> Optional[Tuple[Tuple[int, ...], Any]]:
    """(elementwise-max trailing shape, dtype) over every item on every
    device, or None if no device holds any item."""
    max_trailing: Optional[np.ndarray] = None
    dtype = None
    for items in per_device_items:
        for it in items:
            arr = np.asarray(it)
            t = np.asarray(arr.shape[1:], np.int64)
            if max_trailing is None:
                max_trailing, dtype = t, arr.dtype
            else:
                if len(t) != len(max_trailing):
                    raise ValueError(
                        f"ragged list-state items must share rank: {arr.ndim}d vs {1 + len(max_trailing)}d"
                    )
                if arr.dtype != dtype:
                    raise ValueError(
                        f"ragged list-state items must share dtype: {arr.dtype} vs {dtype} "
                        "(a silent cast would diverge from single-device accumulation)"
                    )
                max_trailing = np.maximum(max_trailing, t)
    if max_trailing is None:
        return None
    return tuple(int(x) for x in max_trailing), dtype


def sync_ragged_states(
    reductions: Mapping[str, Union[Reduce, Callable]],
    per_device_states: Sequence[State],
    mesh: Mesh,
    axis_name: str = "data",
    verify_consistency: bool = False,
    owner: Any = None,
) -> State:
    """Combine per-device states whose list leaves are ragged, via one
    in-graph pad-gather-trim per state name.

    ``per_device_states``: one state pytree per mesh device (eager update
    results on that device's input shard).  Tensor leaves are synced with the
    normal reduction table; list ("cat"/None) leaves — tuples holding a
    *device-dependent number* of arrays with *device-dependent shapes* (any
    dimension may differ, e.g. segm masks from different-sized images) — are
    padded in every dim to the mesh max, crossed with a tiled ``all_gather``,
    and re-split, preserving device order (rank order in the reference).
    Returns the replicated global state; re-split list items come back as
    host numpy views (list states are host-side by construction — pushing
    thousands of small per-image arrays back to the device would serialize
    into tiny transfers the downstream compute immediately undoes).
    """
    n_dev = int(mesh.devices.size)
    if int(mesh.shape[axis_name]) != n_dev:
        # the gather shards stacked buffers over axis_name only; on a
        # multi-axis mesh (e.g. data x model) the per-shard blocks would hold
        # several devices' states and the trim offsets would misalign —
        # build a 1-D eval mesh over the devices instead
        raise ValueError(
            f"sync_ragged_states needs a mesh whose '{axis_name}' axis spans all its devices: "
            f"axis size {int(mesh.shape[axis_name])} != {n_dev} devices. Use a 1-D mesh "
            f"(e.g. parallel.metric_mesh()) for ragged metric-state sync."
        )
    if len(per_device_states) != n_dev:
        raise ValueError(
            f"need one state per mesh device: got {len(per_device_states)} states for {n_dev} devices"
        )
    if verify_consistency:
        # a device whose update count drifted (lost or duplicated a step —
        # the uneven-restore failure mode) would silently skew the gathered
        # aggregate; catch it before the collective runs
        counts = [int(np.asarray(st.get(_N, 0))) for st in per_device_states]
        if len(set(counts)) > 1:
            from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

            majority = max(set(counts), key=counts.count)
            bad = [d for d, c in enumerate(counts) if c != majority]
            raise ReplicaDivergenceError(
                f"per-device update counts diverged before ragged sync: {counts} "
                f"(devices {bad} disagree with the majority count {majority}). Each device "
                "must see the same number of update steps; a preempted/restored device "
                "likely resumed from the wrong step.",
                leaves=(_N,),
                replicas=bad,
            )
    # reserved counters ride the scalar SUM path without a reduction-table entry
    reductions = dict(reductions)
    reductions.setdefault(_NONFINITE, Reduce.SUM)
    names = list(per_device_states[0].keys())

    # ragged-vs-scalar classification comes from the metric's reduction
    # table, not the runtime type of device 0's leaf (ADVICE r5): CAT/None
    # leaves stored as item tuples are ragged; CAT-reduce *tensor* leaves
    # (fixed-shape concat states) ride the scalar/collective path.  Leaf
    # types must agree across devices — a mismatch would otherwise surface
    # as an inscrutable stack/gather shape error.
    scalar_names: List[str] = []
    ragged_names: List[str] = []
    for name in names:
        if name == _N:
            continue
        tuple_on = [isinstance(st[name], tuple) for st in per_device_states]
        if any(tuple_on) and not all(tuple_on):
            kinds = {d: ("list" if t else "tensor") for d, t in enumerate(tuple_on)}
            raise ValueError(
                f"state leaf {name!r} disagrees across devices — {kinds}: every device must "
                "hold the same leaf kind (a tuple of items for list states, an array for "
                "tensor states) for a ragged sync to line up"
            )
        reduce = reductions.get(name)
        if reduce is None:
            raise ValueError(
                f"state leaf {name!r} has no entry in the reduction table "
                f"(known: {sorted(k for k in reductions)}); cannot classify it for ragged sync"
            )
        is_ragged_reduce = reduce in (Reduce.CAT, Reduce.NONE)
        if tuple_on[0]:
            if not is_ragged_reduce and not callable(reduce):
                raise ValueError(
                    f"state leaf {name!r} holds item tuples but its reduction is {reduce!r}; "
                    "only cat/None (or callable) reductions combine list states"
                )
            ragged_names.append(name)
        else:
            scalar_names.append(name)

    # ---- pack ragged leaves: one (buffer, shape-table) pair per name
    packed: Dict[str, Tuple[np.ndarray, np.ndarray, int, int]] = {}  # name -> (bufs, shapes, L, K)
    for name in ragged_names:
        per_dev = [st[name] for st in per_device_states]
        meta = _ragged_meta(per_dev)
        if meta is None:  # no device holds items for this leaf
            continue
        max_trailing, dtype = meta
        # power-of-two bucketing of every padded dim (core/compile.py): the
        # gather graph re-traces only when a bucket boundary is crossed, not
        # on every batch-geometry change — the shape table still records
        # true item shapes, so the trim below is exact
        max_trailing = tuple(bucket_dim(t) for t in max_trailing)
        bufs, shapes = zip(*[_pack_items(items, max_trailing, dtype) for items in per_dev])
        L = bucket_dim(max(b.shape[0] for b in bufs) or 1)
        K = bucket_dim(max(s.shape[0] for s in shapes) or 1)
        ndim = 1 + len(max_trailing)
        buf_stack = np.zeros((n_dev * L, *max_trailing), dtype)
        shape_stack = np.full((n_dev * K, ndim), -1, np.int32)
        for d in range(n_dev):
            buf_stack[d * L : d * L + bufs[d].shape[0]] = bufs[d]
            shape_stack[d * K : d * K + shapes[d].shape[0]] = shapes[d]
        packed[name] = (buf_stack, shape_stack, L, K)

    scalar_stacks = {
        name: jnp.stack([jnp.asarray(st[name]) for st in per_device_states])
        for name in scalar_names
    }
    # "_n" is reserved-but-optional, matching sync_state's contract
    has_n = _N in per_device_states[0]
    n_stack = jnp.stack(
        [jnp.asarray(st.get(_N, 0), jnp.int32) for st in per_device_states]
    )

    ragged_in = {name: (jnp.asarray(packed[name][0]), jnp.asarray(packed[name][1])) for name in packed}

    scalar_reduces = tuple(sorted(((n, reductions[n]) for n in scalar_names), key=lambda kv: kv[0]))
    fn = compiled_ragged_gather(mesh, axis_name, scalar_reduces, tuple(sorted(ragged_in)), owner=owner)
    with _telemetry.span(owner, "sync"):
        g_scalars, g_n, g_ragged = fn(scalar_stacks, n_stack, ragged_in)
    # `owner=None` lands the sync in the `_unattributed` telemetry row rather
    # than double-counting against a metric some outer caller already credits
    _telemetry.record_sync(owner, reductions, dict(per_device_states[0]), n_dev)

    # ---- trim + re-split on host, preserving device order
    out: State = {name: g_scalars[name] for name in scalar_names}
    if has_n:
        out[_N] = g_n
    for name in ragged_names:
        if name not in packed:  # every device empty
            out[name] = ()
            continue
        _, _, L, K = packed[name]
        buf = np.asarray(g_ragged[name][0])
        shape_tab = np.asarray(g_ragged[name][1])
        items: List[np.ndarray] = []
        for d in range(n_dev):
            dev_shapes = shape_tab[d * K : (d + 1) * K]
            dev_shapes = dev_shapes[dev_shapes[:, 0] >= 0]
            offset = d * L
            for shp in dev_shapes:
                lead = int(shp[0])
                window = (slice(offset, offset + lead),) + tuple(slice(0, int(s)) for s in shp[1:])
                items.append(buf[window])
                offset += lead
        out[name] = tuple(items)
    return out


def sharded_list_update(
    metric: "Metric",  # noqa: F821 — forward ref
    per_device_batches: Sequence[Tuple[Any, ...]],
    mesh: Optional[Mesh] = None,
    axis_name: str = "data",
) -> State:
    """One metric step where each device sees its own (possibly ragged) batch.

    The list-state counterpart of :func:`~torchmetrics_tpu.parallel.sync.sharded_update`:
    ``update_state`` runs eagerly per device shard (list-state updates are
    host-side by construction — the reference's are too), then every partial
    state crosses the mesh through :func:`sync_ragged_states`'s single
    padded all_gather per state.  Returns the replicated global state, ready
    for ``compute_state``.
    """
    from torchmetrics_tpu.core.metric import Metric
    from torchmetrics_tpu.parallel.sync import metric_mesh

    if type(metric).sync_states is not Metric.sync_states:
        # the pad-gather-trim combine below applies the per-leaf reduction
        # table; a metric that overrides sync_states (streaming moments,
        # wrapper fan-out) needs its own cross-shard aggregation, and
        # applying the table instead would be silently wrong
        raise ValueError(
            f"{type(metric).__name__} overrides sync_states, so its states do not combine "
            "leaf-wise under the reduction table. Use sharded_update (tensor states) or sync "
            "its states with the metric's own sync_states inside shard_map."
        )
    mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
    states = [metric.update_state(metric.init_state(), *batch) for batch in per_device_batches]
    return sync_ragged_states(metric._reductions, states, mesh, axis_name, owner=metric)


class DeferredRaggedSync:
    """Per-step local accumulation with the cat-state gather deferred to
    ``compute`` — once per evaluation instead of once per step.

    ``BENCH_r05.json`` put the per-step ragged gather at nearly the cost of
    the update itself (mAP: 12.1 ms sync vs 14.4 ms update; ROUGE: 19.2 ms
    vs 22.1 ms on the 8-device mesh).  Cat states don't combine across steps
    — items only concatenate — so gathering them every step moves the same
    bytes ``n_steps`` times for no semantic gain (the arXiv:2004.13336
    argument: per-step replicated reduction work should be deferred or
    distributed).  This accumulator keeps one running state *per device*,
    merges each step's partial state locally (cheap, collective-free), and
    crosses the mesh exactly once when the result is needed.

    Example::

        acc = DeferredRaggedSync(map_metric, mesh=mesh)
        for per_device_batches in loader:
            acc.update(per_device_batches)       # no collective here
        results = acc.compute()                  # ONE padded gather
    """

    def __init__(
        self,
        metric: "Metric",  # noqa: F821 — forward ref
        mesh: Optional[Mesh] = None,
        axis_name: str = "data",
        verify_consistency: bool = False,
    ) -> None:
        from torchmetrics_tpu.core.metric import Metric
        from torchmetrics_tpu.parallel.sync import metric_mesh

        if type(metric).sync_states is not Metric.sync_states:
            raise ValueError(
                f"{type(metric).__name__} overrides sync_states; its states do not combine "
                "leaf-wise under the reduction table, so the deferred gather cannot apply it."
            )
        self.metric = metric
        self.mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
        self.axis_name = axis_name
        self.verify_consistency = verify_consistency
        self._per_device: Optional[List[State]] = None

    @property
    def steps(self) -> int:
        return 0 if self._per_device is None else int(self._per_device[0].get(_N, 0))

    def update(self, per_device_batches: Sequence[Tuple[Any, ...]]) -> None:
        """Fold one step's per-device batches into the running per-device
        states.  Purely local: no cross-device collective runs here."""
        # validated on EVERY step: the merge below zips against the running
        # per-device states, and a silent zip-truncation would drop data
        if len(per_device_batches) != int(self.mesh.devices.size):
            raise ValueError(
                f"need one batch per mesh device: got {len(per_device_batches)} for "
                f"{int(self.mesh.devices.size)} devices"
            )
        m = self.metric
        partial = [m.update_state(m.init_state(), *batch) for batch in per_device_batches]
        if self._per_device is None:
            self._per_device = partial
        else:
            self._per_device = [
                m.merge_states(acc, new) for acc, new in zip(self._per_device, partial)
            ]

    def sync(self) -> State:
        """The one deferred collective: pad-gather-trim every accumulated
        per-device state across the mesh and return the global state."""
        if self._per_device is None:
            raise RuntimeError("DeferredRaggedSync.sync called before any update")
        return sync_ragged_states(
            self.metric._reductions,
            self._per_device,
            self.mesh,
            self.axis_name,
            verify_consistency=self.verify_consistency,
            owner=self.metric,
        )

    def compute(self) -> Any:
        return self.metric.compute_state(self.sync())

    def reset(self) -> None:
        self._per_device = None
