"""Ragged (variable-length) list-state sync across a device mesh.

The hardest sync path in the reference is detection mAP's per-image
variable-length cat states: each rank holds a *different number* of
per-image tensors with *different shapes*, and its custom ``_sync_dist``
pads every tensor to the world max, all_gathers, and trims
(/root/reference/src/torchmetrics/detection/mean_ap.py:1022-1046 via
``gather_all_tensors``'s pad-gather-trim slow path,
/root/reference/src/torchmetrics/utilities/distributed.py:136-147).

The TPU-native equivalent here: per-device list states are packed into ONE
padded buffer + one per-item shape table per state name (items are padded in
*every* dimension to the mesh max, like the reference's all-dims pad), then
every state's buffer of a given dtype is raveled into a single flat buffer —
one tiled ``all_gather`` per *dtype* crosses the mesh inside ``shard_map``
(ICI — not one collective per tensor like the reference's per-tensor
gather), plus one for the shared shape tables.  Scalar (psum/pmax/...)
states ride the same shard_map call through the coalescing planner's dtype
buckets, so a metric mixing tensor and list states syncs in one graph with
a handful of collectives regardless of its leaf count.

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.core.reductions import Reduce
    >>> from torchmetrics_tpu.parallel import metric_mesh, sync_ragged_states
    >>> mesh = metric_mesh()
    >>> n_dev = mesh.devices.size
    >>> # each device holds a DIFFERENT number of variable-length items
    >>> per_dev = [{"items": (jnp.full((d % 3 + 1,), float(d)),)} for d in range(n_dev)]
    >>> merged = sync_ragged_states({"items": Reduce.CAT}, per_dev, mesh)
    >>> len(merged["items"]) == n_dev  # every device's item arrived, in order
    True
    >>> [int(v.shape[0]) for v in merged["items"]] == [d % 3 + 1 for d in range(n_dev)]
    True
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from torchmetrics_tpu.core.compile import bucket_dim, compiled_ragged_gather
from torchmetrics_tpu.core.reductions import Reduce
from torchmetrics_tpu.observability import registry as _telemetry

State = Dict[str, Any]
_N = "_n"
_NONFINITE = "_nonfinite"

#: gather-family lowering routes: ``"flat"`` crosses every chip's shard in
#: one mesh-wide tiled all-gather; ``"two_stage"`` all-gathers over ICI
#: inside each host first, then exchanges ONE aggregated copy per host over
#: DCN — cross-host bytes scale with hosts, not chips
#: (``utilities.benchmark.two_stage_gather_bytes``, arxiv 2204.06514).
GATHER_ROUTES = ("flat", "two_stage")


def _host_combine(reduce: Any, gathered: np.ndarray) -> Any:
    """Apply one leaf's reduction to its DCN-gathered ``(n_hosts, ...)``
    stack — the injectable-allgather counterpart of
    :func:`core.reductions.host_sync_leaf` (which hardwires
    ``process_allgather``)."""
    from torchmetrics_tpu.core.reductions import SketchReduce

    g = jnp.asarray(gathered)
    if isinstance(reduce, SketchReduce):
        if reduce.bucket_op == "sum":
            return g.sum(0)
        if reduce.bucket_op == "max":
            return g.max(0)
        if reduce.bucket_op == "min":
            return g.min(0)
        return reduce.combine_stacked(g)
    if callable(reduce) and not isinstance(reduce, Reduce):
        return reduce(g)
    if reduce == Reduce.SUM:
        return g.sum(0)
    if reduce == Reduce.MEAN:
        return g.mean(0)
    if reduce == Reduce.MAX:
        return g.max(0)
    if reduce == Reduce.MIN:
        return g.min(0)
    raise ValueError(
        f"two-stage DCN exchange cannot combine scalar reduction {reduce!r}; "
        "gather-family leaves cross as flat buffers, not scalars"
    )


def _pack_items(
    items: Sequence[Any], max_trailing: Tuple[int, ...], dtype
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a device's items to the global trailing dims and concatenate along
    the leading axis.  Returns (buffer, shapes) with shapes (k, ndim)."""
    ndim = 1 + len(max_trailing)
    shapes = np.zeros((len(items), ndim), np.int32)
    padded = []
    for j, it in enumerate(items):
        arr = np.asarray(it)
        if arr.ndim != ndim:
            raise ValueError(
                f"ragged list-state items must share rank: got {arr.ndim}d item among {ndim}d items"
            )
        shapes[j] = arr.shape
        pad = [(0, 0)] + [(0, m - s) for m, s in zip(max_trailing, arr.shape[1:])]
        padded.append(np.pad(arr, pad) if any(p != (0, 0) for p in pad) else arr)
    if padded:
        buf = np.concatenate(padded, axis=0)
    else:
        buf = np.zeros((0, *max_trailing), dtype)
    return buf.astype(dtype, copy=False), shapes


def _ragged_meta(per_device_items: Sequence[Sequence[Any]]) -> Optional[Tuple[Tuple[int, ...], Any]]:
    """(elementwise-max trailing shape, dtype) over every item on every
    device, or None if no device holds any item."""
    max_trailing: Optional[np.ndarray] = None
    dtype = None
    for items in per_device_items:
        for it in items:
            arr = np.asarray(it)
            t = np.asarray(arr.shape[1:], np.int64)
            if max_trailing is None:
                max_trailing, dtype = t, arr.dtype
            else:
                if len(t) != len(max_trailing):
                    raise ValueError(
                        f"ragged list-state items must share rank: {arr.ndim}d vs {1 + len(max_trailing)}d"
                    )
                if arr.dtype != dtype:
                    raise ValueError(
                        f"ragged list-state items must share dtype: {arr.dtype} vs {dtype} "
                        "(a silent cast would diverge from single-device accumulation)"
                    )
                max_trailing = np.maximum(max_trailing, t)
    if max_trailing is None:
        return None
    return tuple(int(x) for x in max_trailing), dtype


def _check_update_counts(counts: Sequence[int], leaf: str = _N) -> None:
    """Raise :class:`ReplicaDivergenceError` if the per-device update counts
    disagree (the uneven-restore failure mode — a lost or duplicated step
    would silently skew the gathered aggregate)."""
    if len(set(counts)) > 1:
        from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

        majority = max(set(counts), key=counts.count)
        bad = [d for d, c in enumerate(counts) if c != majority]
        raise ReplicaDivergenceError(
            f"per-device update counts diverged before ragged sync: {counts} "
            f"(devices {bad} disagree with the majority count {majority}). Each device "
            "must see the same number of update steps; a preempted/restored device "
            "likely resumed from the wrong step.",
            leaves=(leaf,),
            replicas=bad,
        )


def _check_value_range(
    per_dev: Sequence[Sequence[Any]], name: str, value_range: Tuple[float, float]
) -> None:
    """Raise if any item of a bitpacked leaf falls outside its declared
    range — a narrowing cast would silently wrap the out-of-range values."""
    lo, hi = value_range
    for d, items in enumerate(per_dev):
        for it in items:
            arr = np.asarray(it)
            if arr.size and (arr.min() < lo or arr.max() > hi):
                raise ValueError(
                    f"ragged leaf {name!r} on device {d} holds values in "
                    f"[{arr.min()}, {arr.max()}] outside its declared value_range "
                    f"({lo}, {hi}); the bitpacked gather would wrap them. Fix the "
                    "add_state(value_range=...) declaration or the update inputs."
                )


def sync_ragged_states(
    reductions: Mapping[str, Union[Reduce, Callable]],
    per_device_states: Sequence[State],
    mesh: Mesh,
    axis_name: str = "data",
    verify_consistency: bool = False,
    owner: Any = None,
    value_ranges: Optional[Mapping[str, Tuple[float, float]]] = None,
    route: str = "flat",
    n_processes: Optional[int] = None,
    dcn_allgather: Optional[Callable[[Any], Any]] = None,
) -> State:
    """Combine per-device states whose list leaves are ragged, via one
    in-graph pad-gather-trim per state name.

    ``per_device_states``: one state pytree per mesh device (eager update
    results on that device's input shard).  Tensor leaves are synced with the
    normal reduction table; list ("cat"/None) leaves — tuples holding a
    *device-dependent number* of arrays with *device-dependent shapes* (any
    dimension may differ, e.g. segm masks from different-sized images) — are
    padded in every dim to the mesh max, crossed with a tiled ``all_gather``,
    and re-split, preserving device order (rank order in the reference).
    Returns the replicated global state; re-split list items come back as
    host numpy views (list states are host-side by construction — pushing
    thousands of small per-image arrays back to the device would serialize
    into tiny transfers the downstream compute immediately undoes).

    ``value_ranges`` (``{leaf: (lo, hi)}``, normally the metric's
    ``add_state(value_range=...)`` declarations) bitpacks integer cat leaves
    for the wire crossing: a leaf whose declared range fits a narrower int
    dtype travels at that width (detection labels in ``[0, 80]`` gather as
    uint8 — a 4x cut) and is cast back after the trim.  The width is static
    — derived from the declaration, never the data — so the gather trace
    stays cache-stable; declared ranges are a contract, validated against
    the data only under ``verify_consistency=True``.

    ``route`` picks the gather lowering (:data:`GATHER_ROUTES`).  ``"flat"``
    (default) crosses every chip's shard in the mesh-wide tiled all-gather
    above.  ``"two_stage"`` keeps that gather *inside the host* (ICI) and
    follows it with ONE per-host exchange over DCN: each host ships its
    aggregated copy once, so cross-host bytes scale with hosts, not chips
    (``utilities.benchmark.two_stage_gather_bytes``'s model; scalar leaves
    re-reduce host-side the way ``coalesced_host_sync`` does).
    ``n_processes``/``dcn_allgather`` are injectable for single-process
    testing, defaulting to ``jax.process_count()`` and
    ``multihost_utils.process_allgather``; with one process the DCN stage
    is skipped and both routes lower identically.
    """
    if route not in GATHER_ROUTES:
        raise ValueError(f"Arg `route` must be one of {GATHER_ROUTES}, got {route!r}")
    if route == "two_stage":
        n_proc = jax.process_count() if n_processes is None else int(n_processes)
    else:
        n_proc = 1
    n_dev = int(mesh.devices.size)
    if int(mesh.shape[axis_name]) != n_dev:
        # the gather shards stacked buffers over axis_name only; on a
        # multi-axis mesh (e.g. data x model) the per-shard blocks would hold
        # several devices' states and the trim offsets would misalign —
        # build a 1-D eval mesh over the devices instead
        raise ValueError(
            f"sync_ragged_states needs a mesh whose '{axis_name}' axis spans all its devices: "
            f"axis size {int(mesh.shape[axis_name])} != {n_dev} devices. Use a 1-D mesh "
            f"(e.g. parallel.metric_mesh()) for ragged metric-state sync."
        )
    if len(per_device_states) != n_dev:
        raise ValueError(
            f"need one state per mesh device: got {len(per_device_states)} states for {n_dev} devices"
        )
    if verify_consistency:
        # a device whose update count drifted (lost or duplicated a step —
        # the uneven-restore failure mode) would silently skew the gathered
        # aggregate; catch it before the collective runs
        _check_update_counts([int(np.asarray(st.get(_N, 0))) for st in per_device_states])
    # reserved counters ride the scalar SUM path without a reduction-table entry
    reductions = dict(reductions)
    reductions.setdefault(_NONFINITE, Reduce.SUM)
    names = list(per_device_states[0].keys())

    # ragged-vs-scalar classification comes from the metric's reduction
    # table, not the runtime type of device 0's leaf (ADVICE r5): CAT/None
    # leaves stored as item tuples are ragged; CAT-reduce *tensor* leaves
    # (fixed-shape concat states) ride the scalar/collective path.  Leaf
    # types must agree across devices — a mismatch would otherwise surface
    # as an inscrutable stack/gather shape error.
    scalar_names: List[str] = []
    ragged_names: List[str] = []
    for name in names:
        if name == _N:
            continue
        tuple_on = [isinstance(st[name], tuple) for st in per_device_states]
        if any(tuple_on) and not all(tuple_on):
            kinds = {d: ("list" if t else "tensor") for d, t in enumerate(tuple_on)}
            raise ValueError(
                f"state leaf {name!r} disagrees across devices — {kinds}: every device must "
                "hold the same leaf kind (a tuple of items for list states, an array for "
                "tensor states) for a ragged sync to line up"
            )
        reduce = reductions.get(name)
        if reduce is None:
            raise ValueError(
                f"state leaf {name!r} has no entry in the reduction table "
                f"(known: {sorted(k for k in reductions)}); cannot classify it for ragged sync"
            )
        is_ragged_reduce = reduce in (Reduce.CAT, Reduce.NONE)
        if tuple_on[0]:
            if not is_ragged_reduce and not callable(reduce):
                raise ValueError(
                    f"state leaf {name!r} holds item tuples but its reduction is {reduce!r}; "
                    "only cat/None (or callable) reductions combine list states"
                )
            ragged_names.append(name)
        else:
            scalar_names.append(name)

    # ---- pack ragged leaves: one (buffer, shape-table) pair per name
    packed: Dict[str, Tuple[np.ndarray, np.ndarray, int, int]] = {}  # name -> (bufs, shapes, L, K)
    unpacked_dtype: Dict[str, Any] = {}  # name -> original dtype when bitpacked
    for name in ragged_names:
        per_dev = [st[name] for st in per_device_states]
        meta = _ragged_meta(per_dev)
        if meta is None:  # no device holds items for this leaf
            continue
        max_trailing, dtype = meta
        if value_ranges and name in value_ranges:
            from torchmetrics_tpu.core.reductions import cat_wire_dtype

            narrow = cat_wire_dtype(dtype, value_ranges[name])
            if narrow != dtype:
                if verify_consistency:
                    _check_value_range(per_dev, name, value_ranges[name])
                unpacked_dtype[name] = dtype
                dtype = narrow
        # power-of-two bucketing of every padded dim (core/compile.py): the
        # gather graph re-traces only when a bucket boundary is crossed, not
        # on every batch-geometry change — the shape table still records
        # true item shapes, so the trim below is exact
        max_trailing = tuple(bucket_dim(t) for t in max_trailing)
        bufs, shapes = zip(*[_pack_items(items, max_trailing, dtype) for items in per_dev])
        L = bucket_dim(max(b.shape[0] for b in bufs) or 1)
        K = bucket_dim(max(s.shape[0] for s in shapes) or 1)
        ndim = 1 + len(max_trailing)
        buf_stack = np.zeros((n_dev * L, *max_trailing), dtype)
        shape_stack = np.full((n_dev * K, ndim), -1, np.int32)
        for d in range(n_dev):
            buf_stack[d * L : d * L + bufs[d].shape[0]] = bufs[d]
            shape_stack[d * K : d * K + shapes[d].shape[0]] = shapes[d]
        packed[name] = (buf_stack, shape_stack, L, K)

    scalar_stacks = {
        name: jnp.stack([jnp.asarray(st[name]) for st in per_device_states])
        for name in scalar_names
    }
    # "_n" is reserved-but-optional, matching sync_state's contract
    has_n = _N in per_device_states[0]
    n_stack = jnp.stack(
        [jnp.asarray(st.get(_N, 0), jnp.int32) for st in per_device_states]
    )

    # ---- coalesce the packed per-name buffers into per-dtype flat gathers:
    # every cat leaf of one dtype ravels into ONE stacked 1-D buffer (each
    # device's segment concatenates its per-name blocks in sorted-name
    # order), and all shape tables share one i32 buffer — however many list
    # states the metric carries, the graph runs one tiled all_gather per
    # dtype plus one for the tables.  Block sizes are functions of the
    # pow2-bucketed L/K/trailing dims, so the flat lengths are as
    # trace-stable as the per-name buffers were.
    sorted_ragged = sorted(packed)
    by_dtype: Dict[str, List[str]] = {}
    for name in sorted_ragged:
        by_dtype.setdefault(str(packed[name][0].dtype), []).append(name)
    # one device's ravel length for this leaf: L * prod(trailing dims)
    block_size = {
        name: packed[name][2] * int(np.prod(packed[name][0].shape[1:], dtype=np.int64))
        for name in sorted_ragged
    }
    shape_block = {name: packed[name][3] * packed[name][1].shape[1] for name in sorted_ragged}

    flats: Dict[str, np.ndarray] = {}
    for dtype_str, group in sorted(by_dtype.items()):
        seg_len = sum(block_size[nm] for nm in group)
        flat = np.zeros((n_dev * seg_len,), np.dtype(dtype_str))
        for d in range(n_dev):
            off = d * seg_len
            for nm in group:
                buf_stack, _, L, _ = packed[nm]
                block = buf_stack[d * L : (d + 1) * L].ravel()
                flat[off : off + block.size] = block
                off += block.size
        flats[f"items_{dtype_str}"] = flat
    if sorted_ragged:
        tab_len = sum(shape_block[nm] for nm in sorted_ragged)
        shp_flat = np.empty((n_dev * tab_len,), np.int32)
        for d in range(n_dev):
            off = d * tab_len
            for nm in sorted_ragged:
                _, shape_stack, _, K = packed[nm]
                block = shape_stack[d * K : (d + 1) * K].ravel()
                shp_flat[off : off + block.size] = block
                off += block.size
        flats["shapes"] = shp_flat
    flats_jnp = {key: jnp.asarray(v) for key, v in flats.items()}

    scalar_reduces = tuple(sorted(((n, reductions[n]) for n in scalar_names), key=lambda kv: kv[0]))
    fn = compiled_ragged_gather(mesh, axis_name, scalar_reduces, tuple(sorted(flats_jnp)), owner=owner)
    # while the gather plane is armed, block inside the span so the measured
    # window covers the collective itself (the way SyncStepper's psum windows
    # already measure), then land per-leaf gather/<leaf> measured_us rows
    measuring = _telemetry.enabled() and _telemetry.gather_armed()
    t0 = time.perf_counter() if measuring else 0.0  # tmt: ignore[TMT006] -- measured gather cost at the host boundary; outside any traced graph
    with _telemetry.span(owner, "sync"):
        g_scalars, g_n, g_flats = fn(scalar_stacks, n_stack, flats_jnp)
        if measuring:
            jax.block_until_ready((g_scalars, g_n, g_flats))
    # `owner=None` lands the sync in the `_unattributed` telemetry row rather
    # than double-counting against a metric some outer caller already credits
    _telemetry.record_sync(owner, reductions, dict(per_device_states[0]), n_dev)

    # ---- stage 2 (two_stage route): ONE aggregated copy per host over DCN —
    # the gather-family counterpart of coalesced_host_sync's bucket exchange.
    # Scalar leaves are already ICI-reduced, so they re-reduce host-side;
    # flat buffers concatenate host-major, extending the device-major carve
    # below to world rank order.
    g_host = {key: np.asarray(v) for key, v in g_flats.items()}
    n_total = n_dev
    if n_proc > 1:
        if dcn_allgather is None:  # pragma: no cover - exercised on real multi-host
            from jax.experimental import multihost_utils

            dcn_allgather = multihost_utils.process_allgather
        g_host = {
            key: np.asarray(dcn_allgather(buf)).reshape(-1) for key, buf in g_host.items()
        }
        g_scalars = {
            name: _host_combine(
                reductions[name], np.asarray(dcn_allgather(np.asarray(g_scalars[name])))
            )
            for name in scalar_names
        }
        g_n = jnp.asarray(np.asarray(dcn_allgather(np.asarray(g_n))).sum(0))
        n_total = n_dev * n_proc
    if measuring:
        measured_s = time.perf_counter() - t0  # tmt: ignore[TMT006] -- measured gather cost at the host boundary; outside any traced graph
        # one row per ragged leaf, sized at its per-chip padded wire block
        # (what the tiled all_gather actually ships), plus the shared shape
        # table — keys match record_measured_sync's gather/<leaf> rows
        leaf_sizes: Dict[str, Tuple[int, int]] = {
            name: (
                block_size[name],
                block_size[name] * packed[name][0].dtype.itemsize,
            )
            for name in sorted_ragged
        }
        if sorted_ragged:
            tab = sum(shape_block[nm] for nm in sorted_ragged)
            leaf_sizes["shapes"] = (tab, tab * 4)
        _telemetry.record_measured_gather(
            owner,
            leaf_sizes,
            n_total,
            measured_s,
            route=route,
            n_hosts=n_proc,
            n_local_devices=n_dev,
        )
        # same window, process-wide: the fleet plane's straggler
        # attribution compares this digest across hosts
        _telemetry.record_sync_wait(measured_s)

    # ---- carve each name's per-device blocks back out of the gathered flats
    rebuilt: Dict[str, np.ndarray] = {}
    for dtype_str, group in sorted(by_dtype.items()):
        seg_len = sum(block_size[nm] for nm in group)
        flat = g_host[f"items_{dtype_str}"]
        for nm in group:
            trail = packed[nm][0].shape[1:]
            rebuilt[nm] = np.empty((n_total * packed[nm][2], *trail), np.dtype(dtype_str))
        for d in range(n_total):
            off = d * seg_len
            for nm in group:
                L = packed[nm][2]
                trail = packed[nm][0].shape[1:]
                size = block_size[nm]
                rebuilt[nm][d * L : (d + 1) * L] = flat[off : off + size].reshape(L, *trail)
                off += size
    shape_tabs: Dict[str, np.ndarray] = {}
    if sorted_ragged:
        tab_len = sum(shape_block[nm] for nm in sorted_ragged)
        shp = g_host["shapes"]
        for nm in sorted_ragged:
            shape_tabs[nm] = np.empty((n_total * packed[nm][3], packed[nm][1].shape[1]), np.int32)
        for d in range(n_total):
            off = d * tab_len
            for nm in sorted_ragged:
                K, ndim = packed[nm][3], packed[nm][1].shape[1]
                size = shape_block[nm]
                shape_tabs[nm][d * K : (d + 1) * K] = shp[off : off + size].reshape(K, ndim)
                off += size

    # ---- trim + re-split on host, preserving device order
    out: State = {name: g_scalars[name] for name in scalar_names}
    if has_n:
        out[_N] = g_n
    for name in ragged_names:
        if name not in packed:  # every device empty
            out[name] = ()
            continue
        _, _, L, K = packed[name]
        buf = rebuilt[name]
        if name in unpacked_dtype:  # bitpacked wire crossing: restore the declared dtype
            buf = buf.astype(unpacked_dtype[name])
        shape_tab = shape_tabs[name]
        items: List[np.ndarray] = []
        for d in range(n_total):
            dev_shapes = shape_tab[d * K : (d + 1) * K]
            dev_shapes = dev_shapes[dev_shapes[:, 0] >= 0]
            offset = d * L
            for shp in dev_shapes:
                lead = int(shp[0])
                window = (slice(offset, offset + lead),) + tuple(slice(0, int(s)) for s in shp[1:])
                items.append(buf[window])
                offset += lead
        out[name] = tuple(items)
    return out


def sharded_list_update(
    metric: "Metric",  # noqa: F821 — forward ref
    per_device_batches: Sequence[Tuple[Any, ...]],
    mesh: Optional[Mesh] = None,
    axis_name: str = "data",
) -> State:
    """One metric step where each device sees its own (possibly ragged) batch.

    The list-state counterpart of :func:`~torchmetrics_tpu.parallel.sync.sharded_update`:
    ``update_state`` runs eagerly per device shard (list-state updates are
    host-side by construction — the reference's are too), then every partial
    state crosses the mesh through :func:`sync_ragged_states`'s single
    padded all_gather per state.  Returns the replicated global state, ready
    for ``compute_state``.
    """
    from torchmetrics_tpu.core.metric import Metric
    from torchmetrics_tpu.parallel.sync import metric_mesh

    if type(metric).sync_states is not Metric.sync_states:
        # the pad-gather-trim combine below applies the per-leaf reduction
        # table; a metric that overrides sync_states (streaming moments,
        # wrapper fan-out) needs its own cross-shard aggregation, and
        # applying the table instead would be silently wrong
        raise ValueError(
            f"{type(metric).__name__} overrides sync_states, so its states do not combine "
            "leaf-wise under the reduction table. Use sharded_update (tensor states) or sync "
            "its states with the metric's own sync_states inside shard_map."
        )
    mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
    states = [metric.update_state(metric.init_state(), *batch) for batch in per_device_batches]
    return sync_ragged_states(
        metric._reductions,
        states,
        mesh,
        axis_name,
        owner=metric,
        value_ranges=getattr(metric, "_value_ranges", None),
    )


class DeferredRaggedSync:
    """Per-step local accumulation with the cat-state gather deferred to
    ``compute`` — once per evaluation instead of once per step.

    ``BENCH_r05.json`` put the per-step ragged gather at nearly the cost of
    the update itself (mAP: 12.1 ms sync vs 14.4 ms update; ROUGE: 19.2 ms
    vs 22.1 ms on the 8-device mesh).  Cat states don't combine across steps
    — items only concatenate — so gathering them every step moves the same
    bytes ``n_steps`` times for no semantic gain (the arXiv:2004.13336
    argument: per-step replicated reduction work should be deferred or
    distributed).  This accumulator keeps one running state *per device*,
    merges each step's partial state locally (cheap, collective-free), and
    crosses the mesh exactly once when the result is needed.

    Several cat-state metrics sharing one evaluation loop can
    :meth:`register` on the SAME accumulator: their leaves are namespaced
    (``"name::leaf"``) into one combined state, so ``sync`` runs a single
    coalesced gather — one all_gather per dtype — for ALL of them instead of
    one gather per metric.

    Example::

        acc = DeferredRaggedSync(map_metric, mesh=mesh)
        for per_device_batches in loader:
            acc.update(per_device_batches)       # no collective here
        results = acc.compute()                  # ONE padded gather

        shared = DeferredRaggedSync(mesh=mesh)
        shared.register(map_metric, "map")
        shared.register(rouge_metric, "rouge")
        ...
        shared.update_for("map", map_batches)    # still no collective
        shared.update_for("rouge", rouge_batches)
        results = shared.compute()               # ONE gather for both
    """

    def __init__(
        self,
        metric: Optional["Metric"] = None,  # noqa: F821 — forward ref
        mesh: Optional[Mesh] = None,
        axis_name: str = "data",
        verify_consistency: bool = False,
        route: str = "flat",
        n_processes: Optional[int] = None,
        dcn_allgather: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        from torchmetrics_tpu.parallel.sync import metric_mesh

        self.mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
        self.axis_name = axis_name
        self.verify_consistency = verify_consistency
        if route not in GATHER_ROUTES:
            raise ValueError(f"Arg `route` must be one of {GATHER_ROUTES}, got {route!r}")
        #: gather lowering for :meth:`sync` — ``"flat"`` or ``"two_stage"``
        #: (:data:`GATHER_ROUTES`); flip at runtime with :meth:`set_route`
        self.route = route
        #: injectable DCN seam (``coalesced_host_sync``'s contract): default
        #: ``jax.process_count()`` / ``multihost_utils.process_allgather``
        self.n_processes = n_processes
        self.dcn_allgather = dcn_allgather
        self._members: Dict[str, Any] = {}  # insertion-ordered
        self._per_device: Dict[str, Optional[List[State]]] = {}
        if metric is not None:
            self.register(metric)

    def set_route(self, route: str) -> str:
        """Switch the gather lowering for subsequent :meth:`sync` calls;
        returns the previous route (the GatherAdvisor's rollback token).
        Accumulated per-device states are untouched — only the crossing
        changes."""
        if route not in GATHER_ROUTES:
            raise ValueError(f"Arg `route` must be one of {GATHER_ROUTES}, got {route!r}")
        previous, self.route = self.route, route
        return previous

    def register(self, metric: "Metric", name: Optional[str] = None) -> str:  # noqa: F821
        """Add a metric to the shared deferred gather; returns its key.

        Idempotent per metric object: registering the SAME metric again
        under its existing name (a snapshot→restore path re-running setup)
        is a no-op returning the original key — the accumulated per-device
        states are kept and nothing double-gathers.  Registering a
        *different* metric under an occupied name raises."""
        from torchmetrics_tpu.core.metric import Metric

        if type(metric).sync_states is not Metric.sync_states:
            raise ValueError(
                f"{type(metric).__name__} overrides sync_states; its states do not combine "
                "leaf-wise under the reduction table, so the deferred gather cannot apply it."
            )
        if name is None:
            name = type(metric).__name__
            if name in self._members and self._members[name] is not metric:
                name = f"{name}_{len(self._members)}"
        if name in self._members:
            if self._members[name] is metric:
                return name  # same metric, same name: setup re-ran, keep state
            raise ValueError(
                f"a different {type(self._members[name]).__name__} is already registered "
                f"under {name!r}; pass an explicit unique name (re-registering the SAME "
                "metric object is a no-op, but two metrics cannot share a telemetry owner name)"
            )
        if "::" in name:
            raise ValueError(f"metric name {name!r} may not contain '::' (the namespace separator)")
        self._members[name] = metric
        self._per_device[name] = None
        return name

    @property
    def metric(self) -> "Metric":  # noqa: F821
        """The sole registered metric (single-metric back-compat accessor)."""
        if len(self._members) != 1:
            raise AttributeError(
                f".metric needs exactly one registered metric, have {len(self._members)}"
            )
        return next(iter(self._members.values()))

    def _sole_key(self, what: str) -> str:
        if len(self._members) != 1:
            raise RuntimeError(
                f"{what} requires exactly one registered metric "
                f"(have {sorted(self._members)}); use the *_for/keyed variants"
            )
        return next(iter(self._members))

    @property
    def steps(self) -> int:
        key = self._sole_key("steps")
        states = self._per_device[key]
        return 0 if states is None else int(states[0].get(_N, 0))

    def update(self, per_device_batches: Sequence[Tuple[Any, ...]]) -> None:
        """Fold one step's per-device batches into the running per-device
        states.  Purely local: no cross-device collective runs here."""
        self.update_for(self._sole_key("update"), per_device_batches)

    def update_for(self, name: str, per_device_batches: Sequence[Tuple[Any, ...]]) -> None:
        """:meth:`update` for one registered metric of a shared accumulator."""
        if name not in self._members:
            raise KeyError(f"no metric registered under {name!r} (have {sorted(self._members)})")
        # validated on EVERY step: the merge below zips against the running
        # per-device states, and a silent zip-truncation would drop data
        m = self._members[name]
        n_dev = int(self.mesh.devices.size)
        got = len(per_device_batches)
        if got != n_dev:
            if got < n_dev:
                detail = f"devices {list(range(got, n_dev))} would receive no batch"
            else:
                detail = f"batches {list(range(n_dev, got))} have no device"
            raise ValueError(
                f"{type(m).__name__} (registered as {name!r}) needs one batch per mesh "
                f"device: got {got} batches for {n_dev} devices — {detail}"
            )
        partial = [m.update_state(m.init_state(), *batch) for batch in per_device_batches]
        if self._per_device[name] is None:
            self._per_device[name] = partial
        else:
            self._per_device[name] = [
                m.merge_states(acc, new) for acc, new in zip(self._per_device[name], partial)
            ]
        if _telemetry.enabled() and _telemetry.gather_armed():
            # live cat-state attribution: this step's appended elements/bytes
            # per gather-family leaf (summed over the local mesh — matching
            # the bench's whole-update cat_state_bytes_per_step accounting)
            # plus the running totals for the high-watermark
            from torchmetrics_tpu.observability.gathers import cat_growth_rows

            _telemetry.record_cat_growth(
                m, cat_growth_rows(m, partial, self._per_device[name])
            )

    def sync(self) -> Union[State, Dict[str, State]]:
        """The one deferred collective: pad-gather-trim every accumulated
        per-device state across the mesh.  With one registered metric,
        returns its global state (back-compat); with several, returns
        ``{name: state}`` — all of them crossed in a single coalesced
        gather."""
        if not self._members:
            raise RuntimeError("DeferredRaggedSync.sync called with no registered metric")
        never = [k for k, v in self._per_device.items() if v is None]
        if never:
            raise RuntimeError(
                f"DeferredRaggedSync.sync called before any update for {never}"
            )
        if len(self._members) == 1:
            key = next(iter(self._members))
            m = self._members[key]
            # raw (un-namespaced) leaf names keep the single-metric compile
            # cache keys identical to the pre-registration API
            return sync_ragged_states(
                m._reductions,
                self._per_device[key],
                self.mesh,
                self.axis_name,
                verify_consistency=self.verify_consistency,
                owner=m,
                value_ranges=getattr(m, "_value_ranges", None),
                route=self.route,
                n_processes=self.n_processes,
                dcn_allgather=self.dcn_allgather,
            )
        n_dev = int(self.mesh.devices.size)
        if self.verify_consistency:
            for key, states in self._per_device.items():
                _check_update_counts(
                    [int(np.asarray(st.get(_N, 0))) for st in states], leaf=f"{key}::{_N}"
                )
        table: Dict[str, Union[Reduce, Callable]] = {}
        ranges: Dict[str, Tuple[float, float]] = {}
        combined: List[State] = [{} for _ in range(n_dev)]
        for key, m in self._members.items():
            table.update({f"{key}::{leaf}": r for leaf, r in m._reductions.items()})
            ranges.update(
                {f"{key}::{leaf}": rng for leaf, rng in getattr(m, "_value_ranges", {}).items()}
            )
            # reserved counters become ordinary namespaced SUM leaves — the
            # combined state has no top-level "_n" of its own
            table[f"{key}::{_N}"] = Reduce.SUM
            table[f"{key}::{_NONFINITE}"] = Reduce.SUM
            for d, st in enumerate(self._per_device[key]):
                combined[d].update({f"{key}::{leaf}": v for leaf, v in st.items()})
        # owner=None: the sync spans several metrics, so it lands in the
        # `_unattributed` telemetry row instead of crediting one of them
        synced = sync_ragged_states(
            table,
            combined,
            self.mesh,
            self.axis_name,
            owner=None,
            value_ranges=ranges,
            route=self.route,
            n_processes=self.n_processes,
            dcn_allgather=self.dcn_allgather,
        )
        out: Dict[str, State] = {}
        for key in self._members:
            prefix = f"{key}::"
            out[key] = {
                leaf[len(prefix):]: v for leaf, v in synced.items() if leaf.startswith(prefix)
            }
        return out

    def compute(self) -> Any:
        """Single metric: its computed value.  Several: ``{name: value}``."""
        if len(self._members) == 1:
            return self.metric.compute_state(self.sync())
        synced = self.sync()
        return {key: self._members[key].compute_state(synced[key]) for key in self._members}

    def reset(self) -> None:
        self._per_device = {key: None for key in self._members}

    def reset_for(self, name: str) -> None:
        """Drop one member's accumulated per-device states (the others keep
        theirs).  The GatherAdvisor calls this when committing an approx
        conversion mid-run: ``set_approx`` rebuilds the metric's leaves, so
        the exact partials accumulated under the old layout cannot merge
        with post-conversion updates."""
        if name not in self._members:
            raise KeyError(f"no metric registered under {name!r} (have {sorted(self._members)})")
        self._per_device[name] = None
