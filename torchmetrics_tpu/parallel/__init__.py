"""Distributed backend: in-graph collectives over a device mesh + host-level DCN sync.

The reference's entire comm backend is ``gather_all_tensors``
(/root/reference/src/torchmetrics/utilities/distributed.py:97-147) over
``torch.distributed``.  Here the equivalent surface is:

* :func:`sync_state` / :func:`sync_leaf` — in-graph, inside shard_map/pjit,
  lowering to XLA collectives over ICI;
* :func:`gather_all_arrays` — host-level all-gather across processes (DCN);
* :func:`metric_mesh`, :func:`sharded_update` — mesh construction and a
  one-call helper that runs a metric ``update`` on batch-sharded inputs and
  psum-merges the partial states;
* :func:`sync_ragged_states` / :func:`sharded_list_update` — the
  pad-gather-trim path for ragged list states (detection mAP's per-image
  variable-length tensors; reference ``_sync_dist`` at
  detection/mean_ap.py:1022-1046 + utilities/distributed.py:136-147);
* :mod:`~torchmetrics_tpu.parallel.coalesce` — the sync planner behind all
  of the above: dtype-bucketed fused collectives (:func:`build_sync_plan` /
  :func:`apply_sync_plan`), sync cadence control (:class:`SyncPolicy`,
  :class:`SyncStepper`, :func:`flush_sync`), and the hierarchical
  ICI-then-DCN host sync (:func:`coalesced_host_sync`);
* :mod:`~torchmetrics_tpu.parallel.compress` — opt-in compressed collectives
  (:class:`CompressionConfig` / per-bucket :class:`CompressionSpec`): bf16 or
  two-phase int8 quantized bucket all-reduces and bitpacked ragged gathers,
  surfaced through ``SyncPolicy(compression=..., error_budget=...)``;
* :mod:`~torchmetrics_tpu.parallel.autotune` — the closed control loop over
  all of the above (:class:`SyncAutotuner`): sets :class:`SyncPolicy` on
  running flows from live telemetry through an observe → candidate → trial →
  commit | rollback state machine, with flight-recorded decisions, a JSONL
  decision ledger, and health-monitor/divergence guardrails.  Report-only by
  default, like :class:`SyncAdvisor`.
"""

from torchmetrics_tpu.parallel.autotune import (
    SyncAutotuner,
    committed_policy,
    policy_dict,
)
from torchmetrics_tpu.parallel.compress import CompressionConfig, CompressionSpec
from torchmetrics_tpu.parallel.coalesce import (
    SyncAdvisor,
    SyncPolicy,
    SyncStepper,
    apply_sync_plan,
    bucketed_collective_count,
    build_sync_plan,
    cadence_stepper,
    coalesced_host_sync,
    coalesced_metric_sync,
    coalesced_sync_state,
    flush_sync,
    per_leaf_collective_count,
)
from torchmetrics_tpu.parallel.ragged import (
    DeferredRaggedSync,
    sharded_list_update,
    sync_ragged_states,
)
from torchmetrics_tpu.parallel.sync import (
    distributed_available,
    gather_all_arrays,
    metric_mesh,
    reduce as reduce_op,
    sharded_collection_update,
    sharded_update,
    sync_state,
)

__all__ = [
    "CompressionConfig",
    "CompressionSpec",
    "DeferredRaggedSync",
    "SyncAdvisor",
    "SyncAutotuner",
    "SyncPolicy",
    "SyncStepper",
    "apply_sync_plan",
    "bucketed_collective_count",
    "build_sync_plan",
    "cadence_stepper",
    "coalesced_host_sync",
    "coalesced_metric_sync",
    "coalesced_sync_state",
    "committed_policy",
    "distributed_available",
    "flush_sync",
    "gather_all_arrays",
    "metric_mesh",
    "per_leaf_collective_count",
    "policy_dict",
    "reduce_op",
    "sharded_collection_update",
    "sharded_list_update",
    "sharded_update",
    "sync_ragged_states",
    "sync_state",
]
