"""Closed-loop sync autotuning: measured telemetry in, committed ``SyncPolicy`` out.

:class:`~torchmetrics_tpu.parallel.coalesce.SyncAdvisor` (PR 6/8/10) measures
candidate cadences, models per-mode wire bytes, and folds fleet skew — but it
only *prints* advice.  :class:`SyncAutotuner` promotes that advice to an
in-band controller that **sets** the policy on a running
:class:`~torchmetrics_tpu.parallel.coalesce.SyncStepper` /
``sharded_update(sync_policy=...)`` flow, through an explicit state machine
whose every transition is itself an observable event::

                 propose()              arm()                commit()
    observe  ───────────────▶ candidate ──────▶ trial ─────────────────▶ committed
       ▲                                          │                          │
       │          veto (health alert / divergence │ / manual)               │
       └──────────────────────────────────────────┘                          │
       ◀──────────────────── rollback (guardrail / manual) ──────────────────┘

Safety properties, in decreasing order of importance:

* **Report-only by default.**  Like the advisor, a ``SyncAutotuner()`` never
  mutates anything: ``commit()`` ledgers the decision with ``applied: false``.
  Pass ``report_only=False`` to let commits actually set the policy.
* **Guardrails are in-band.**  Wire ``monitor.add_sink(tuner.guardrail_sink())``
  and any :class:`~torchmetrics_tpu.observability.health.HealthMonitor` alert
  at/above ``veto_severity`` vetoes a pending trial or rolls back a committed
  policy the moment it fires; a
  :class:`~torchmetrics_tpu.utilities.exceptions.ReplicaDivergenceError` from
  the divergence verifier does the same through :meth:`report_divergence`.
  The veto/rollback is itself a ledgered decision.
* **Trace-safe transitions.**  ``every_n`` is *not* part of the cadence
  compile-cache keys (the pending counter is host-side), so cadence commits
  reuse the existing carry with zero new compile-cache entries; a compression
  change keys a new ``cadence_sync`` entry, so it is ledgered against its
  known one-time ``new-key`` miss (``expected_retraces``) and
  :meth:`retrace_report` proves the accounting against
  ``cache_stats()['miss_causes']``.
* **Every decision is observable** three ways: Chrome-trace instant events
  under the ``"policy"`` category in the flight recorder, the queryable
  :meth:`decision_ledger` (JSONL through the export front door, stamped with
  ``schema_version`` + process identity), and ``tm_tpu_autotune_*``
  Prometheus families rendered from :meth:`report`.
"""

import copy
from typing import Any, Dict, List, Mapping, Optional, Sequence

from torchmetrics_tpu.parallel.coalesce import (
    SyncAdvisor,
    SyncPolicy,
    SyncStepper,
)
from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

__all__ = [
    "AUTOTUNE_ACTIONS",
    "AUTOTUNE_STATES",
    "SyncAutotuner",
    "committed_policy",
    "policy_dict",
]

#: the state machine's states, in commit order
AUTOTUNE_STATES = ("observe", "candidate", "trial", "committed")
#: every action a ledger entry may carry
AUTOTUNE_ACTIONS = (
    "observe",
    "propose",
    "arm",
    "commit",
    "veto",
    "rollback",
    "audit",
)

#: ``kind`` stamp on every ledger entry (JSONL consumers filter on it)
LEDGER_KIND = "autotune_decision"


def policy_dict(policy: Optional[SyncPolicy]) -> Optional[Dict[str, Any]]:
    """Stable JSON shape of a :class:`SyncPolicy` for ledger/export payloads."""
    if policy is None:
        return None
    return {
        "every_n": None if policy.at_compute else policy.every_n_steps,
        "at_compute": bool(policy.at_compute),
        "compression": policy.compression,
        "error_budget": policy.error_budget,
    }


def committed_policy(target: Any) -> Optional[SyncPolicy]:
    """The policy a :class:`SyncAutotuner` committed onto ``target`` —
    ``sharded_update``/``sharded_collection_update`` consult this override
    before the hand-passed ``sync_policy``.  ``None`` without a commit."""
    return target.__dict__.get("_autotuned_policy")


class SyncAutotuner:
    """Drive :class:`SyncPolicy` for one metric/collection from live telemetry.

    ``target`` is the metric or collection whose sync path is tuned, or a
    :class:`SyncStepper` already driving it (the stepper's mesh/axis/policy
    are then adopted).  The tuned knobs are the ``every_n`` cadence, the
    compression mode within the declared ``error_budget``, and the ICI/DCN
    two-stage host-sync toggle (decided from fleet skew + the DCN byte
    model; exposed as :attr:`two_stage` for ``coalesced_host_sync`` callers).

    Example (the walkthrough in ``examples/autotune_walkthrough.py``)::

        tuner = SyncAutotuner(stepper, report_only=False, error_budget=1e-2)
        monitor.add_sink(tuner.guardrail_sink())   # alerts veto/roll back

        tuner.observe(preds, target, steps=16)     # measure candidates
        tuner.propose()                            # pick a candidate policy
        tuner.arm()                                # stage it for commit
        tuner.commit()                             # guarded policy switch
        tuner.decision_ledger()                    # every decision, queryable
    """

    def __init__(
        self,
        target: Any,
        mesh: Optional[Any] = None,
        axis_name: str = "data",
        candidates: Sequence[int] = (1, 2, 4, 8),
        target_cut: float = 3.5,
        max_staleness: int = 8,
        error_budget: Optional[float] = None,
        report_only: bool = True,
        veto_severity: str = "warning",
        in_specs: Optional[Any] = None,
    ) -> None:
        from torchmetrics_tpu.observability.health import _severity_rank
        from torchmetrics_tpu.parallel.sync import metric_mesh

        if isinstance(target, SyncStepper):
            self._stepper: Optional[SyncStepper] = target
            self.target = target.target
            self.mesh = target.mesh
            self.axis_name = target.axis_name
            self.in_specs = target.in_specs
        else:
            self._stepper = None
            self.target = target
            self.mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
            self.axis_name = axis_name
            self.in_specs = in_specs
        _severity_rank(veto_severity)  # validates
        self.veto_severity = veto_severity
        self.report_only = bool(report_only)
        self.target_cut = float(target_cut)
        self.error_budget = error_budget
        self.advisor = SyncAdvisor(
            self.target,
            mesh=self.mesh,
            axis_name=self.axis_name,
            in_specs=self.in_specs,
            candidates=candidates,
            max_staleness=max_staleness,
            error_budget=error_budget,
        )
        self.state = "observe"
        #: committed two-stage ICI/DCN decision (None until a commit carries one)
        self.two_stage: Optional[bool] = None
        self._seq = 0
        self._ledger: List[Dict[str, Any]] = []
        self._candidate: Optional[Dict[str, Any]] = None
        self._previous: Optional[SyncPolicy] = None  # policy to roll back to
        self._commit_cache_baseline: Optional[Dict[str, Any]] = None
        self._expected_retraces: Dict[str, Any] = {"new_keys": 0, "cause": None}
        self.counts: Dict[str, int] = {
            "observations": 0,
            "proposals": 0,
            "trials": 0,
            "commits": 0,
            "transitions": 0,
            "vetoes": 0,
            "rollbacks": 0,
        }

    # ------------------------------------------------------------- live flow
    def _live_stepper(self) -> Optional[SyncStepper]:
        """The stepper actually running: the explicit one, else the cadence
        stepper ``sharded_update(sync_policy=...)`` cached on the target."""
        if self._stepper is not None:
            return self._stepper
        return self.target.__dict__.get("_cadence_stepper")

    def current_policy(self) -> SyncPolicy:
        """The policy the live flow runs under right now."""
        stepper = self._live_stepper()
        if stepper is not None:
            return stepper.policy
        override = committed_policy(self.target)
        return override if override is not None else SyncPolicy()

    # ---------------------------------------------------------- state machine
    def observe(
        self,
        *inputs: Any,
        steps: int = 16,
        rounds: int = 3,
        profile: Optional[Mapping[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Measure the candidate cadences (``SyncAdvisor.profile``) — or adopt
        a prebuilt profile dict — and (re)enter the ``observe`` state."""
        if profile is None:
            profile = self.advisor.profile(*inputs, steps=steps, rounds=rounds)
        else:
            self.advisor._profile = dict(profile)
        prior = self.state
        self.state = "observe"
        self._candidate = None
        self.counts["observations"] += 1
        self._record(
            "observe",
            state_from=prior,
            trigger={
                "steps": profile.get("steps"),
                "n_devices": profile.get("n_devices"),
                "cadences": [r["every_n"] for r in profile.get("runs", ())],
            },
            rationale="measured candidate cadences under live telemetry",
        )
        return dict(profile)

    def propose(
        self, target_cut: Optional[float] = None, fleet: Optional[Any] = None
    ) -> Dict[str, Any]:
        """Derive the candidate policy from the measured profile: the advisor's
        cadence pick, the strongest compression mode within ``error_budget``,
        and the two-stage DCN toggle from fleet context."""
        cut = self.target_cut if target_cut is None else float(target_cut)
        rec = self.advisor.recommend(target_cut=cut, fleet=fleet)
        mode = rec["compression"]["recommended_mode"]
        policy = SyncPolicy(
            every_n_steps=int(rec["every_n"]),
            compression=mode,
            error_budget=self.error_budget if mode != "none" else None,
        )
        two_stage = self._two_stage_advice(fleet)
        if fleet is not None and hasattr(fleet, "straggler_bound"):
            straggler_bound = bool(fleet.straggler_bound())
        else:
            straggler_bound = bool(
                fleet is not None
                and rec.get("fleet", {}).get("wait_skew_ratio", 1.0) >= 2.0
            )
        self._candidate = {
            "policy": policy,
            "two_stage": two_stage,
            "recommendation": rec,
            "straggler_bound": straggler_bound,
        }
        prior = self.state
        self.state = "candidate"
        self.counts["proposals"] += 1
        self._record(
            "propose",
            state_from=prior,
            old_policy=self.current_policy(),
            new_policy=policy,
            trigger={
                "measured_cut": rec["measured_cut"],
                "target_cut": cut,
                "baseline_sync_s": rec["baseline_sync_s"],
                "sync_s": rec["sync_s"],
                "two_stage": two_stage,
            },
            rationale=(
                f"smallest cadence with measured sync cut >= {cut}"
                + (f"; compression {mode} within error budget" if mode != "none" else "")
                + ("; straggler-bound fleet: cadence is the wrong lever" if straggler_bound else "")
            ),
        )
        return self.candidate()

    def candidate(self) -> Optional[Dict[str, Any]]:
        """JSON view of the current candidate (``None`` outside candidate/trial)."""
        if self._candidate is None:
            return None
        out = {
            "policy": policy_dict(self._candidate["policy"]),
            "two_stage": self._candidate["two_stage"],
            "straggler_bound": self._candidate["straggler_bound"],
        }
        return out

    def arm(self) -> Dict[str, Any]:
        """Stage the candidate for commit: enter ``trial``, during which any
        guardrail alert vetoes the pending policy before it ever applies."""
        if self.state != "candidate" or self._candidate is None:
            raise RuntimeError(
                f"SyncAutotuner.arm: no candidate to stage (state {self.state!r}); "
                "call propose() first"
            )
        self.state = "trial"
        self.counts["trials"] += 1
        return self._record(
            "arm",
            state_from="candidate",
            old_policy=self.current_policy(),
            new_policy=self._candidate["policy"],
            rationale="candidate staged; guardrails may veto until commit()",
        )

    def commit(self) -> Dict[str, Any]:
        """Apply the staged candidate to the live flow (or ledger it only, in
        report-only mode).  A guardrail alert that fired during the trial has
        already vetoed it — commit then raises.  Divergence during the policy
        switch itself vetoes and re-raises."""
        if self.state != "trial" or self._candidate is None:
            raise RuntimeError(
                f"SyncAutotuner.commit: no staged trial (state {self.state!r}) — "
                "it may have been vetoed by a guardrail; check decision_ledger()"
            )
        policy = self._candidate["policy"]
        old = self.current_policy()
        expected = self._expected_retraces_for(old, policy)
        applied = not self.report_only
        if applied:
            from torchmetrics_tpu.core.compile import cache_stats

            self._commit_cache_baseline = cache_stats()
            try:
                self._apply(old, policy)
            except ReplicaDivergenceError as err:
                self._veto("divergence", error=str(err))
                raise
        self._previous = old
        self._expected_retraces = expected
        self.two_stage = bool(self._candidate["two_stage"]["enabled"])
        self.state = "committed"
        self.counts["commits"] += 1
        if applied:
            self.counts["transitions"] += 1
        self._count_target("policy_commits")
        entry = self._record(
            "commit",
            state_from="trial",
            old_policy=old,
            new_policy=policy,
            applied=applied,
            trigger=self._candidate_trigger(),
            expected_retraces=expected,
            rationale=(
                "policy committed to live flow"
                if applied
                else "report-only: decision ledgered, policy untouched "
                "(construct with report_only=False to apply)"
            ),
        )
        self._candidate = None
        return entry

    def veto(self, reason: str = "manual", alert: Optional[Any] = None) -> Dict[str, Any]:
        """Veto the pending trial (guardrails call this through
        :meth:`guardrail_sink`; callers may veto manually)."""
        if self.state != "trial":
            raise RuntimeError(
                f"SyncAutotuner.veto: no pending trial to veto (state {self.state!r})"
            )
        return self._veto(reason, alert=alert)

    def rollback(
        self,
        reason: str = "manual",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Restore the pre-commit policy on the live flow and ledger why."""
        if self.state != "committed" or self._previous is None:
            raise RuntimeError(
                f"SyncAutotuner.rollback: nothing committed to roll back "
                f"(state {self.state!r})"
            )
        committed = self.current_policy() if not self.report_only else None
        restore = self._previous
        if not self.report_only:
            self._apply(committed, restore)
        self.counts["rollbacks"] += 1
        self._count_target("policy_rollbacks")
        entry = self._record(
            "rollback",
            state_from="committed",
            state_to="observe",
            old_policy=committed,
            new_policy=restore,
            applied=not self.report_only,
            alert=alert,
            error=error,
            rationale=f"rolled back committed policy: {reason}",
        )
        self.state = "observe"
        self._previous = None
        self.two_stage = None
        return entry

    # ------------------------------------------------------------- guardrails
    def guardrail_sink(self, min_severity: Optional[str] = None) -> Any:
        """An ``AlertSink`` that wires :class:`HealthMonitor` alerts into the
        control loop: ``monitor.add_sink(tuner.guardrail_sink())``.  Alerts
        at/above ``min_severity`` (default: the tuner's ``veto_severity``)
        veto a pending trial or roll back a committed policy, in-band."""
        from torchmetrics_tpu.observability.health import CallbackAlertSink

        return CallbackAlertSink(
            self._on_alert,
            min_severity=self.veto_severity if min_severity is None else min_severity,
        )

    def _on_alert(self, alert: Any) -> None:
        if self.state == "trial":
            self._veto("health_alert", alert=alert)
        elif self.state == "committed" and self._previous is not None:
            self.rollback(reason="health_alert", alert=alert)

    def attach_shadow_auditor(
        self,
        exact_twin: Any,
        *,
        sample_rate: float = 1.0 / 16.0,
        seed: int = 0,
        min_severity: Optional[str] = None,
        **kwargs: Any,
    ) -> Any:
        """A :class:`~torchmetrics_tpu.observability.accuracy.ShadowAuditor`
        on this tuner's target whose breach alerts feed straight into
        :meth:`guardrail_sink` — the measured-error guardrail: a shadow-exact
        audit observing more error than the committed policy's predicted
        bound vetoes the trial or rolls the commit back, in-band."""
        from torchmetrics_tpu.observability.accuracy import ShadowAuditor

        return ShadowAuditor(
            self.target,
            exact_twin,
            sample_rate=sample_rate,
            seed=seed,
            sinks=[self.guardrail_sink(min_severity)],
            **kwargs,
        )

    def report_divergence(self, error: Exception) -> Optional[Dict[str, Any]]:
        """Feed a :class:`ReplicaDivergenceError` raised by the divergence
        verifier into the loop: veto the pending trial or roll back the
        committed policy.  Returns the ledgered decision (``None`` when the
        loop has nothing to act on)."""
        if self.state == "trial":
            return self._veto("divergence", error=str(error))
        if self.state == "committed" and self._previous is not None:
            return self.rollback(reason="divergence", error=str(error))
        return None

    def _veto(
        self, reason: str, alert: Optional[Any] = None, error: Optional[str] = None
    ) -> Dict[str, Any]:
        vetoed = self._candidate["policy"] if self._candidate else None
        self.counts["vetoes"] += 1
        self._count_target("policy_vetoes")
        entry = self._record(
            "veto",
            state_from=self.state,
            state_to="observe",
            old_policy=self.current_policy(),
            new_policy=vetoed,
            applied=False,
            alert=alert,
            error=error,
            rationale=f"pending commit vetoed: {reason}",
        )
        self.state = "observe"
        self._candidate = None
        return entry

    # ------------------------------------------------------------ application
    def _apply(self, old: Optional[SyncPolicy], policy: SyncPolicy) -> None:
        """Switch the live flow to ``policy``.

        ``every_n``-only changes apply mid-window (the pending counter simply
        compares against the new threshold; the cadence compile keys do not
        contain ``every_n``, so the carry and its compiled step/sync are
        reused verbatim).  A compression change first flushes the open window
        so it syncs under the policy it accumulated under — the one new
        ``cadence_sync`` key then keys the *next* window's sync.
        """
        stepper = self._live_stepper()
        if stepper is not None:
            if (
                old is not None
                and stepper.pending
                and policy.compression != old.compression
            ):
                stepper.sync()  # may raise ReplicaDivergenceError -> veto in commit()
            stepper.policy = policy
        # future cadence_stepper resolutions (sharded_update flows) pick the
        # committed policy up through this override, even when the caller
        # still passes the stale hand-chosen one
        self.target.__dict__["_autotuned_policy"] = policy

    def _expected_retraces_for(
        self, old: SyncPolicy, new: SyncPolicy
    ) -> Dict[str, Any]:
        if old.compression == new.compression:
            return {"new_keys": 0, "cause": None, "entrypoint": None}
        # compression joins the cadence_sync cache key: exactly one new-key
        # miss when the first window under the new mode syncs
        return {"new_keys": 1, "cause": "new-key", "entrypoint": "cadence"}

    def retrace_report(self) -> Dict[str, Any]:
        """Compile-cache delta since the last applied commit, judged against
        the ledgered expectation — the proof that a cadence transition was
        retrace-free and a compression transition cost exactly its known
        ``new-key`` miss.  Ledgered as an ``audit`` decision."""
        from torchmetrics_tpu.core.compile import cache_stats_since

        if self._commit_cache_baseline is None:
            raise RuntimeError(
                "SyncAutotuner.retrace_report: no applied commit to audit "
                "(report-only commits never touch the cache)"
            )
        delta = cache_stats_since(self._commit_cache_baseline)
        delta_causes = delta["miss_causes"]
        extra_traces = int(delta["traces"])
        extra_misses = int(delta["misses"])
        expected = self._expected_retraces
        ok = (
            extra_misses <= expected["new_keys"]
            and sum(delta_causes.values()) <= expected["new_keys"]
            and all(cause == expected["cause"] for cause in delta_causes)
        )
        audit = {
            "extra_traces": extra_traces,
            "extra_misses": extra_misses,
            "miss_causes": delta_causes,
            "expected": dict(expected),
            "ok": bool(ok),
        }
        self._record(
            "audit",
            state_from=self.state,
            state_to=self.state,
            trigger=audit,
            rationale=(
                "trace-safety audit: cache delta since commit matches the "
                "ledgered expectation"
                if ok
                else "trace-safety audit FAILED: unexpected compile-cache traffic "
                "since commit"
            ),
        )
        return audit

    # ----------------------------------------------------------- observability
    def decision_ledger(self) -> List[Dict[str, Any]]:
        """Every decision this tuner took, oldest first — stable schema
        (``kind == "autotune_decision"``), safe to mutate."""
        return copy.deepcopy(self._ledger)

    def export_ledger(
        self, path: Optional[str] = None, stream: Optional[Any] = None
    ) -> List[str]:
        """Write the ledger through the export front door: one JSONL line per
        decision, each stamped with ``schema_version`` + process identity and
        parseable back via ``observability.parse_export_line``."""
        from torchmetrics_tpu.observability.export import JSONLinesExporter

        exporter = JSONLinesExporter(path=path, stream=stream)
        return [exporter.export(entry) for entry in self._ledger]

    def report(self) -> Dict[str, Any]:
        """The ``autotune`` block for the export front door: merge it into a
        telemetry report (``report["autotune"] = tuner.report()``) and the
        Prometheus exporter renders the ``tm_tpu_autotune_*`` families."""
        return {
            "state": self.state,
            "report_only": self.report_only,
            "policy": policy_dict(self.current_policy()),
            "two_stage": self.two_stage,
            "counts": dict(self.counts),
            "decisions": len(self._ledger),
        }

    # -------------------------------------------------------------- internals
    def _candidate_trigger(self) -> Dict[str, Any]:
        rec = self._candidate["recommendation"]
        return {
            "measured_cut": rec["measured_cut"],
            "baseline_sync_s": rec["baseline_sync_s"],
            "sync_s": rec["sync_s"],
            "sync_wire_bytes": rec["sync_wire_bytes"],
            "two_stage": self._candidate["two_stage"],
        }

    def _two_stage_advice(self, fleet: Optional[Any]) -> Dict[str, Any]:
        """Decide the ICI/DCN two-stage toggle: pays only with >1 process, by
        the DCN byte model (``two_stage_dcn_bytes``)."""
        from torchmetrics_tpu.utilities.benchmark import two_stage_dcn_bytes

        skew = None
        if fleet is not None:
            skew = fleet.skew() if hasattr(fleet, "skew") else dict(fleet)
        n_proc = int(skew.get("n_processes", 1)) if skew else 1
        if n_proc <= 1:
            return {
                "enabled": False,
                "n_processes": n_proc,
                "rationale": "single process: no DCN stage to coalesce",
            }
        flat = two = 0
        n_local = max(int(self.mesh.devices.size) // n_proc, 1)
        for m in self.advisor._member_metrics():
            dcn = two_stage_dcn_bytes(
                m._reductions, m._state, n_hosts=n_proc, n_local_devices=n_local
            )
            flat += dcn["flat"]
            two += dcn["two_stage"]
        enabled = two > 0 and flat > two
        return {
            "enabled": bool(enabled),
            "n_processes": n_proc,
            "model_flat_bytes": int(flat),
            "model_two_stage_bytes": int(two),
            "model_cut": round(flat / two, 2) if two else None,
            "rationale": (
                "two-stage ICI/DCN sync cuts modelled cross-host bytes"
                if enabled
                else "flat host sync is already minimal for this state"
            ),
        }

    def _count_target(self, name: str) -> None:
        from torchmetrics_tpu.observability import registry as _telemetry

        _telemetry.count(self.target, name)

    def _record(
        self,
        action: str,
        state_from: str,
        state_to: Optional[str] = None,
        old_policy: Optional[SyncPolicy] = None,
        new_policy: Optional[SyncPolicy] = None,
        applied: Optional[bool] = None,
        trigger: Optional[Mapping[str, Any]] = None,
        rationale: str = "",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
        expected_retraces: Optional[Mapping[str, Any]] = None,
    ) -> Dict[str, Any]:
        entry: Dict[str, Any] = {
            "kind": LEDGER_KIND,
            "seq": self._seq,
            "action": action,
            "state_from": state_from,
            "state_to": self.state if state_to is None else state_to,
            "old_policy": policy_dict(old_policy),
            "new_policy": policy_dict(new_policy),
            "applied": bool(applied) if applied is not None else None,
            "report_only": self.report_only,
            "trigger": dict(trigger) if trigger else {},
            "rationale": rationale,
        }
        if alert is not None:
            entry["alert"] = alert.as_dict() if hasattr(alert, "as_dict") else dict(alert)
        if error is not None:
            entry["error"] = error
        if expected_retraces is not None:
            entry["expected_retraces"] = dict(expected_retraces)
        self._seq += 1
        self._ledger.append(entry)
        self._flight_record(entry)
        return copy.deepcopy(entry)

    def _flight_record(self, entry: Mapping[str, Any]) -> None:
        """Chrome-trace instant under the ``policy`` category — old/new
        policy, trigger measurement, and rationale ride the args."""
        from torchmetrics_tpu.observability import tracing

        if not tracing.active():
            return
        rec = tracing.recorder()
        if rec is None:  # pragma: no cover - active() already checked
            return
        rec.instant(
            f"policy/{entry['action']}",
            "policy",
            seq=entry["seq"],
            state_from=entry["state_from"],
            state_to=entry["state_to"],
            old_policy=entry["old_policy"],
            new_policy=entry["new_policy"],
            applied=entry["applied"],
            trigger=entry["trigger"],
            rationale=entry["rationale"],
        )
