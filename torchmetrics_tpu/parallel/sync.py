"""Mesh helpers and the metric-state sync backend.

TPU-native replacement for the reference's distributed layer
(/root/reference/src/torchmetrics/utilities/distributed.py and the
``Metric._sync_dist`` protocol at metric.py:435-474):

* cross-device sync is a *pure function* on the state pytree — there is no
  sync/unsync cache-restore dance (metric.py:544-571) because nothing is
  mutated in place;
* inside jit, reductions lower to single XLA collectives over a named mesh
  axis (ICI);
* across hosts (eager facade), ``multihost_utils.process_allgather`` rides
  DCN.

Example::

    >>> import jax.numpy as jnp
    >>> from torchmetrics_tpu.classification import BinaryAccuracy
    >>> from torchmetrics_tpu.parallel import metric_mesh, sharded_update
    >>> mesh = metric_mesh()  # 1-D mesh over all local devices
    >>> metric = BinaryAccuracy(validate_args=False)
    >>> probs = jnp.asarray([0.9, 0.2, 0.8, 0.4, 0.7, 0.1, 0.6, 0.3])
    >>> target = jnp.asarray([1, 0, 1, 0, 0, 0, 1, 1])
    >>> state = sharded_update(metric, probs, target, mesh=mesh)  # batch-split + in-graph psum
    >>> round(float(metric.compute_state(state)), 4)
    0.75
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax.experimental import multihost_utils

from torchmetrics_tpu.core.reductions import Reduce
from torchmetrics_tpu.observability import registry as _telemetry
from torchmetrics_tpu.parallel.coalesce import (
    CompressionConfig,
    SyncPolicy,
    cadence_stepper,
    coalesced_host_sync,
    coalesced_sync_state,
)
from torchmetrics_tpu.utilities.prints import rank_zero_debug, rank_zero_warn

State = Dict[str, Any]

_N = "_n"
_NONFINITE = "_nonfinite"

# one-time latch for the distributed_available probe failure, so a broken
# backend logs once instead of on every compute()
_DIST_PROBE_FAILED_LOGGED = False

# one-time-per-class latch for the uncached kwargs path warning below
_KWARGS_RETRACE_WARNED: set = set()


def distributed_available() -> bool:
    """True when more than one process participates (multi-host program).

    The reference's probe is ``torch.distributed.is_initialized``
    (metric.py:46-48); the JAX equivalent is the process count.  Only a
    ``RuntimeError`` (the backend is not initialized / no devices) means
    "not distributed" — anything else is a real failure and propagates.
    """
    global _DIST_PROBE_FAILED_LOGGED
    try:
        return jax.process_count() > 1
    except RuntimeError as err:  # pragma: no cover - needs an uninitialized backend
        if not _DIST_PROBE_FAILED_LOGGED:
            _DIST_PROBE_FAILED_LOGGED = True
            rank_zero_debug(
                "jax.process_count() raised %r; treating the program as single-process.", err
            )
        return False


def metric_mesh(n_devices: Optional[int] = None, axis_name: str = "data") -> Mesh:
    """Build a 1-D device mesh for data-parallel metric evaluation."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices).reshape(len(devices)), (axis_name,))


def sync_state(
    state: State,
    reductions: Mapping[str, Union[Reduce, Callable]],
    axis_name: str = "data",
    compression: Optional[CompressionConfig] = None,
) -> State:
    """In-graph sync: combine every leaf of ``state`` across ``axis_name``.

    Pure; call inside ``shard_map``/``pmap``.  The per-leaf reduction table is
    the same one ``merge`` uses, so in-graph sync and local merge are
    guaranteed consistent (the reference re-implements both paths separately
    at metric.py:401 and :459).  Lowers through the coalescing planner
    (``parallel.coalesce``): one collective per (dtype, reduction-class)
    bucket instead of one per leaf; reserved counters (``_n``/``_nonfinite``)
    ride the int32 sum bucket.
    """
    return coalesced_sync_state(state, reductions, axis_name, compression=compression)


def host_sync_state(
    state: State,
    reductions: Mapping[str, Union[Reduce, Callable]],
    compression: Optional[CompressionConfig] = None,
) -> State:
    """Cross-process sync of an eager state pytree (DCN path, no jit).

    Bucketed like the in-graph path: one ``process_allgather`` per
    (dtype, reduction-class) bucket — the DCN stage of the hierarchical
    two-stage reduce, crossing hosts on already ICI-reduced state.
    ``compression`` shrinks eligible buckets' DCN payloads (see
    :func:`~torchmetrics_tpu.parallel.coalesce.coalesced_host_sync`).
    """
    return coalesced_host_sync(state, reductions, compression=compression)


def gather_all_arrays(value: Array, group: Any = None) -> list:
    """Host-level all-gather of one array across processes.

    Equivalent of ``gather_all_tensors``
    (/root/reference/src/torchmetrics/utilities/distributed.py:97-147).  The
    reference pads+trims for uneven shapes; ``process_allgather`` handles
    shape negotiation itself, so the fast/slow split disappears.
    Returns a list of per-process arrays.

    ``group`` (the reference's ``torch.distributed`` process group) has no
    JAX equivalent — ``process_allgather`` always spans every process — so a
    non-``None`` group is rejected instead of silently ignored.
    """
    if group is not None:
        raise ValueError(
            "gather_all_arrays(group=...) is not supported: JAX's process_allgather "
            "always spans all processes; there is no process-subgroup equivalent. "
            "Pass group=None and filter the returned per-process list instead."
        )
    if not distributed_available():
        return [value]
    gathered = multihost_utils.process_allgather(value)
    return list(gathered)


def reduce(x: Array, reduction: str = "elementwise_mean") -> Array:
    """Reduce a tensor: elementwise_mean | sum | none.

    Reference: utilities/distributed.py:22-42.
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Class-aware num/denom reduction: micro | macro | weighted | none.

    Reference: utilities/distributed.py:45-94.
    """
    valid = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid}")


def _measured_sync_dispatch(
    owner: Any,
    fn: Callable[..., Any],
    inputs: Sequence[Any],
    mesh: Mesh,
    entries_of: Optional[Callable[[Any], Any]] = None,
    compression: Optional[CompressionConfig] = None,
    shardings: Any = None,
) -> Any:
    """Dispatch one compiled sharded sync under the owner's ``"sync"`` span.

    While telemetry is on, the dispatch is block-until-ready'd *inside* the
    span so the measured wall time covers the collective itself rather than
    just its async enqueue, and the window is attributed per-bucket through
    :func:`observability.registry.record_measured_sync`.  Dark (telemetry
    off), dispatch stays fully async — cadence/pipelining is unchanged.
    """
    measuring = _telemetry.enabled()
    t0 = time.perf_counter() if measuring else 0.0  # tmt: ignore[TMT006] -- measured sync cost at the host boundary; outside any traced graph
    with _telemetry.span(owner, "sync"):
        out = fn(*inputs)
        if measuring:
            jax.block_until_ready(out)
    if measuring:
        measured_s = time.perf_counter() - t0  # tmt: ignore[TMT006] -- measured sync cost at the host boundary; outside any traced graph
        entries = entries_of(out) if entries_of is not None else [(owner._reductions, out)]
        _telemetry.record_measured_sync(
            owner,
            entries,
            int(mesh.devices.size),
            measured_s,
            compression=compression,
            shardings=shardings,
        )
        # the same window also feeds the process-wide wait digest the fleet
        # plane ranks hosts by (observability/fleet.py straggler attribution)
        _telemetry.record_sync_wait(measured_s)
    return out


def _sync_states_with(metric: Any, st: State, axis_name: str, compression: Optional[CompressionConfig]) -> State:
    """Route a traced sync through ``metric.sync_states``, forwarding the
    compression config only to the standard (planner-backed) implementation —
    metrics that override ``sync_states`` keep their own exact aggregation."""
    from torchmetrics_tpu.core.metric import Metric

    if compression is not None and type(metric).sync_states is Metric.sync_states:
        return metric.sync_states(st, axis_name, compression=compression)
    return metric.sync_states(st, axis_name)


def sharded_update(
    metric: "Metric",  # noqa: F821 - forward ref, avoids circular import
    *inputs: Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "data",
    in_specs: Optional[Any] = None,
    verify_consistency: bool = False,
    sync_policy: Optional[SyncPolicy] = None,
    on_divergence: str = "raise",
    **kwargs: Array,
) -> State:
    """Run one metric ``update`` with inputs sharded over the mesh batch axis.

    Each device computes a partial state from its input shard; partial states
    are combined in-graph with the metric's reduction table (psum & friends)
    and the replicated global state is returned.  This is the TPU-idiomatic
    replacement for the reference's "each rank holds a replica and all_gathers
    at compute" model (§2.8 of SURVEY.md): the collective runs over ICI inside
    the step graph, so metric accumulation fuses into the eval step.

    With ``verify_consistency=True`` the returned replicated state's
    per-device copies are checksum-compared over the mesh axis
    (:func:`torchmetrics_tpu.resilience.verify_replica_consistency`); a
    device copy that diverged raises
    :class:`~torchmetrics_tpu.utilities.exceptions.ReplicaDivergenceError`
    at sync time instead of producing a silently wrong aggregate.

    ``on_divergence`` picks the failure policy when that check trips:
    ``"raise"`` (default) is fail-stop; ``"quarantine"`` excludes the
    divergent replicas from this and every subsequent sync (masked out of
    the collective via an in-graph weight —
    :mod:`torchmetrics_tpu.resilience.quarantine`), re-dispatches the same
    inputs through the masked graph, and returns the surviving quorum's
    answer — degraded, alerted, never silently wrong.  A metric already
    running degraded keeps using the masked graph even on clean steps.

    With a deferring ``sync_policy`` (``SyncPolicy(every_n_steps=k)`` or
    ``at_compute=True``), repeated calls accumulate *locally* on each device
    and the coalesced collective runs only on sync steps: the call returns
    the **cumulative** replicated state on sync steps and ``None`` on
    deferred ones; finish with
    :func:`~torchmetrics_tpu.parallel.coalesce.flush_sync`.
    """
    if on_divergence not in ("raise", "quarantine"):
        raise ValueError(
            f'on_divergence must be "raise" or "quarantine", got {on_divergence!r}'
        )
    mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
    if in_specs is None:
        in_specs = P(axis_name)

    specs = tuple(in_specs for _ in inputs) if not isinstance(in_specs, tuple) else in_specs
    # a SyncAutotuner commit (parallel/autotune.py) overrides the hand-passed
    # policy: the committed policy wins until it is rolled back, so a running
    # flow keeps calling with its original sync_policy= and still follows the
    # autotuned cadence/compression
    override = metric.__dict__.get("_autotuned_policy")
    if override is not None:
        sync_policy = override
    compression = sync_policy.compression_config if sync_policy is not None else None

    if sync_policy is not None and sync_policy.defers:
        if kwargs:
            raise ValueError(
                "sharded_update(sync_policy=...) needs positional inputs: the cadence "
                "stepper's compiled local step is cached, and kwargs would be frozen as "
                "trace constants"
            )
        stepper = cadence_stepper(
            metric,
            mesh=mesh,
            axis_name=axis_name,
            policy=sync_policy,
            verify_consistency=verify_consistency,
            in_specs=specs,
            on_divergence=on_divergence,
        )
        return stepper.update(*inputs)

    from torchmetrics_tpu.resilience.quarantine import is_degraded

    if (on_divergence == "quarantine" or is_degraded(metric)) and kwargs:
        raise ValueError(
            "sharded_update(on_divergence='quarantine') needs positional inputs: the "
            "masked (degraded-mode) step is a cached compiled variant, and kwargs "
            "would be frozen as trace constants"
        )
    # check_vma=False (inside compiled_sharded_update): all_gather-produced
    # leaves are replicated in value but the static VMA checker cannot infer
    # that, so replication is asserted, not checked.
    if kwargs:
        # kwargs are closed over as trace constants — a cached compile would
        # freeze their first values, so this path stays uncached
        cls_name = type(metric).__name__
        if cls_name not in _KWARGS_RETRACE_WARNED:
            _KWARGS_RETRACE_WARNED.add(cls_name)
            rank_zero_warn(
                f"sharded_update({cls_name}, ...) was called with keyword inputs "
                f"({sorted(kwargs)}). This path cannot use the compile cache — kwargs are "
                "closed over as trace constants — so EVERY step re-traces (~seconds each). "
                "Pass the batch arrays positionally to hit the cached compiled path "
                "(core.compile.compiled_sharded_update). This warning is shown once per "
                "metric class."
            )

        def step(*shards):
            st = metric.update_state(metric.init_state(), *shards, **kwargs)
            # metric.sync_states, not the bare reduction table: metrics with
            # non-distributive states (e.g. Pearson's streaming moments)
            # override sync_states with their own cross-shard aggregation
            return _sync_states_with(metric, st, axis_name, compression)

        from torchmetrics_tpu.core.compile import shard_map

        sharding_table = metric.__dict__.get("_state_shardings") or None
        fn = shard_map(
            step,
            mesh=mesh,
            in_specs=specs,
            out_specs=metric.sync_out_specs(axis_name),
            check_vma=False,
        )
        out = _measured_sync_dispatch(
            metric,
            fn,
            inputs,
            mesh,
            compression=compression,
            shardings=None if not sharding_table else [sharding_table],
        )
        _telemetry.record_sync(
            metric,
            metric._reductions,
            out,
            int(mesh.devices.size),
            compression=compression,
            shardings=sharding_table,
        )
        if verify_consistency:
            from torchmetrics_tpu.resilience.divergence import verify_replica_consistency

            verify_replica_consistency(metric, mesh=mesh, state=out, axis_name=axis_name)
        return out
    # unified compile cache: the compiled step is keyed on (metric class,
    # config fingerprint, mesh, axis, specs, abstract input shapes), so
    # mutating a metric attribute after the first call re-traces with the
    # new config instead of silently reusing the stale step (ADVICE r5),
    # while repeat steps still hit the cache (a fresh shard_map closure per
    # call would re-trace every step, turning a ~100 µs collective into a
    # ~1 s compile)
    from torchmetrics_tpu.core.compile import compiled_sharded_update

    sharding_table = metric.__dict__.get("_state_shardings") or None

    def dispatch() -> State:
        measured_shardings = None if not sharding_table else [sharding_table]
        if is_degraded(metric):
            from torchmetrics_tpu.resilience.quarantine import quarantine_mask

            fn = compiled_sharded_update(
                metric, mesh, axis_name, specs, inputs, compression=compression, masked=True
            )
            mask = quarantine_mask(metric, mesh, axis_name)
            out = _measured_sync_dispatch(
                metric,
                fn,
                (mask,) + inputs,
                mesh,
                compression=compression,
                shardings=measured_shardings,
            )
        else:
            fn = compiled_sharded_update(
                metric, mesh, axis_name, specs, inputs, compression=compression
            )
            out = _measured_sync_dispatch(
                metric, fn, inputs, mesh, compression=compression, shardings=measured_shardings
            )
        _telemetry.record_sync(
            metric,
            metric._reductions,
            out,
            int(mesh.devices.size),
            compression=compression,
            shardings=sharding_table,
        )
        return out

    out = dispatch()
    if verify_consistency:
        from torchmetrics_tpu.resilience.divergence import verify_replica_consistency
        from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

        try:
            verify_replica_consistency(metric, mesh=mesh, state=out, axis_name=axis_name)
        except ReplicaDivergenceError as err:
            out = _quarantine_and_redispatch(
                metric, err, on_divergence, mesh, axis_name, dispatch
            )
    return out


def _quarantine_and_redispatch(
    target: Any,
    err: Exception,
    on_divergence: str,
    mesh: Mesh,
    axis_name: str,
    dispatch: Callable[[], Any],
    verify: Optional[Callable[[Any], None]] = None,
) -> Any:
    """The shared ``on_divergence="quarantine"`` handler.

    Quarantines the replicas the divergence error names, re-runs the same
    inputs through the masked graph, and re-verifies the surviving quorum's
    answer.  Re-raises (never a silent wrong answer) when the policy is
    ``"raise"``, when the divergent replicas cannot be identified, when no
    quorum would survive, or when the masked re-dispatch still diverges.
    """
    from torchmetrics_tpu.resilience.divergence import verify_replica_consistency
    from torchmetrics_tpu.resilience.quarantine import (
        degradation_report,
        quarantine,
        quarantined_replicas,
    )
    from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

    if on_divergence != "quarantine":
        raise err
    replicas = getattr(err, "replicas", None)
    if not replicas:
        raise ReplicaDivergenceError(
            f"{err} (on_divergence='quarantine' needs the divergent replica indices to "
            "mask them out, but the check could not identify them)",
            leaves=getattr(err, "leaves", ()),
        ) from err
    quarantine(target, replicas, reason="divergence")
    n = int(mesh.devices.size)
    # re-stamp the quorum block knowing the mesh size, so the surviving
    # fraction rides telemetry/attestations (quarantine() itself cannot know n)
    _telemetry.record_quorum(target, degradation_report(target, n_devices=n))
    survivors = n - len(quarantined_replicas(target))
    if survivors < 1:
        raise ReplicaDivergenceError(
            f"{err} (quarantining replicas {sorted(replicas)} would leave no surviving "
            f"quorum on the {n}-device mesh)",
            leaves=getattr(err, "leaves", ()),
            replicas=replicas,
        ) from err
    rank_zero_warn(
        f"{type(target).__name__}: replicas {sorted(int(r) for r in replicas)} diverged "
        f"({sorted(getattr(err, 'leaves', ()))}); quarantined — evaluation continues on "
        f"the surviving {survivors}/{n} replicas."
    )
    out = dispatch()
    # the degraded answer must itself be consistent; a second divergence is
    # fail-stop regardless of policy
    if verify is not None:
        verify(out)
    else:
        verify_replica_consistency(target, mesh=mesh, state=out, axis_name=axis_name)
    return out


def sharded_collection_update(
    collection: "MetricCollection",  # noqa: F821 - forward ref, avoids circular import
    *inputs: Array,
    mesh: Optional[Mesh] = None,
    axis_name: str = "data",
    in_specs: Optional[Any] = None,
    sync_policy: Optional[SyncPolicy] = None,
    verify_consistency: bool = False,
    on_divergence: str = "raise",
) -> Dict[str, State]:
    """One fused compiled step for a whole :class:`MetricCollection`.

    Every compute-group leader updates from its input shard AND syncs across
    the mesh inside ONE shard_map graph — one dispatch, and through the
    coalescing planner ONE collective per (dtype, reduction-class) bucket
    *across all leaders* (2 buckets for Acc+F1+AUROC), instead of one
    :func:`sharded_update` dispatch with per-leaf collectives per member
    metric.  Shared preprocessing between members is CSE'd by XLA inside the
    single graph.  Returns ``{leader_name: replicated_state}``, ready for
    ``collection.compute_states`` / ``collection.load_states``.

    ``sync_policy`` (defaulting to the collection's ``sync_policy=``
    construction flag) defers the collective like
    :func:`sharded_update`'s: deferred steps return ``None``, sync steps
    return the cumulative states; finish with
    :func:`~torchmetrics_tpu.parallel.coalesce.flush_sync`.

    ``verify_consistency`` / ``on_divergence`` mirror :func:`sharded_update`:
    the returned replicated states are checksum-compared per leader, and
    ``on_divergence="quarantine"`` masks divergent replicas out of every
    member's sync instead of failing the run.

    Leaders with list (cat) states cannot ride the in-graph step path — use
    :class:`~torchmetrics_tpu.parallel.ragged.DeferredRaggedSync` for those.
    """
    from torchmetrics_tpu.core.compile import compiled_sharded_collection_update

    if on_divergence not in ("raise", "quarantine"):
        raise ValueError(
            f'on_divergence must be "raise" or "quarantine", got {on_divergence!r}'
        )
    mesh = mesh if mesh is not None else metric_mesh(axis_name=axis_name)
    if in_specs is None:
        in_specs = P(axis_name)
    specs = tuple(in_specs for _ in inputs) if not isinstance(in_specs, tuple) else in_specs

    leaders = tuple(members[0] for members in collection._functional_groups().values())
    listy = [name for name in leaders if collection[name]._has_list_states]
    if listy:
        raise ValueError(
            f"sharded_collection_update fuses fixed-size (psum-family) states into one graph; "
            f"leaders {listy} hold list (cat) states, which grow per step and cannot be traced. "
            "Update those eagerly and defer their gather to compute with DeferredRaggedSync."
        )
    if sync_policy is None:
        sync_policy = getattr(collection, "_sync_policy", None)
    # committed SyncAutotuner policy wins over the hand-passed/constructed one
    override = collection.__dict__.get("_autotuned_policy")
    if override is not None:
        sync_policy = override
    compression = sync_policy.compression_config if sync_policy is not None else None
    if sync_policy is not None and sync_policy.defers:
        stepper = cadence_stepper(
            collection,
            mesh=mesh,
            axis_name=axis_name,
            policy=sync_policy,
            verify_consistency=verify_consistency,
            in_specs=specs,
            on_divergence=on_divergence,
        )
        return stepper.update(*inputs)

    from torchmetrics_tpu.resilience.quarantine import is_degraded

    def dispatch() -> Dict[str, State]:
        if is_degraded(collection):
            from torchmetrics_tpu.resilience.quarantine import quarantine_mask

            fn = compiled_sharded_collection_update(
                collection, leaders, mesh, axis_name, specs, inputs,
                compression=compression, masked=True,
            )
            mask = quarantine_mask(collection, mesh, axis_name)
            call_inputs: Tuple[Any, ...] = (mask,) + inputs
        else:
            fn = compiled_sharded_collection_update(
                collection, leaders, mesh, axis_name, specs, inputs, compression=compression
            )
            call_inputs = inputs
        leader_shardings = [
            collection[name].__dict__.get("_state_shardings") or None for name in leaders
        ]
        out = _measured_sync_dispatch(
            collection,
            fn,
            call_inputs,
            mesh,
            entries_of=lambda o: [(collection[name]._reductions, o[name]) for name in leaders],
            compression=compression,
            shardings=leader_shardings if any(leader_shardings) else None,
        )
        if _telemetry.enabled():
            n_dev = int(mesh.devices.size)
            for name, sharding_table in zip(leaders, leader_shardings):
                _telemetry.record_sync(
                    collection[name],
                    collection[name]._reductions,
                    out[name],
                    n_dev,
                    compression=compression,
                    shardings=sharding_table,
                )
        return out

    out = dispatch()
    if verify_consistency:
        from torchmetrics_tpu.resilience.divergence import verify_replica_consistency
        from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

        def verify(states: Dict[str, State]) -> None:
            for name in leaders:
                verify_replica_consistency(
                    collection[name], mesh=mesh, state=states[name], axis_name=axis_name
                )

        try:
            verify(out)
        except ReplicaDivergenceError as err:
            out = _quarantine_and_redispatch(
                collection, err, on_divergence, mesh, axis_name, dispatch, verify=verify
            )
    return out
