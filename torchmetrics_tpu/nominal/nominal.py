"""Nominal-association metric classes.

Reference: nominal/{cramers.py:30, tschuprows.py:30, pearson.py:33,
theils_u.py:30, fleiss_kappa.py:29}.  The χ²-family accumulates a static
(num_classes, num_classes) contingency table (sum/psum-reduced — no ragged
gathers); FleissKappa accumulates per-sample category counts (cat-reduced).
"""

from __future__ import annotations

from typing import Any, Literal, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.nominal.contingency import (
    _cramers_v_compute,
    _nominal_confmat_update,
    _pearsons_contingency_coefficient_compute,
    _theils_u_compute,
    _tschuprows_t_compute,
)
from torchmetrics_tpu.functional.nominal.fleiss_kappa import (
    _fleiss_kappa_compute,
    _fleiss_kappa_update,
)
from torchmetrics_tpu.functional.nominal.utils import _nominal_input_validation
from torchmetrics_tpu.utilities.data import dim_zero_cat

NanStrategy = Literal["replace", "drop"]


class _ContingencyMetric(Metric):
    """Base: (C, C) contingency-table state, statistic evaluated at compute."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        nan_strategy: NanStrategy = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_classes, int) and num_classes > 0):
            raise ValueError(f"Argument `num_classes` must be a positive integer, got {num_classes}")
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.num_classes = num_classes
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state(
            "confmat", jnp.zeros((num_classes, num_classes)), dist_reduce_fx="sum"
        )

    def _update(self, state: State, preds: Array, target: Array) -> State:
        cm = _nominal_confmat_update(
            preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value
        )
        return {"confmat": state["confmat"] + cm}


class CramersV(_ContingencyMetric):
    """Cramér's V association (nominal/cramers.py:30).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import CramersV
        >>> metric = CramersV(num_classes=3)
        >>> metric.update(jnp.asarray([0, 1, 2, 1, 0, 2, 0, 1]), jnp.asarray([0, 1, 2, 2, 0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.5652
    """

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: NanStrategy = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy, nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _compute(self, state: State) -> Array:
        return _cramers_v_compute(state["confmat"], self.bias_correction)


class TschuprowsT(_ContingencyMetric):
    """Tschuprow's T association (nominal/tschuprows.py:30)."""

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: NanStrategy = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy, nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _compute(self, state: State) -> Array:
        return _tschuprows_t_compute(state["confmat"], self.bias_correction)


class PearsonsContingencyCoefficient(_ContingencyMetric):
    """Pearson's contingency coefficient (nominal/pearson.py:33)."""

    def _compute(self, state: State) -> Array:
        return _pearsons_contingency_coefficient_compute(state["confmat"])


class TheilsU(_ContingencyMetric):
    """Theil's U uncertainty coefficient (nominal/theils_u.py:30); asymmetric.
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.nominal import TheilsU
        >>> metric = TheilsU(num_classes=3)
        >>> metric.update(jnp.asarray([0, 1, 2, 1, 0, 2, 0, 1]), jnp.asarray([0, 1, 2, 2, 0, 1, 0, 1]))
        >>> round(float(metric.compute()), 4)
        0.6193
    """

    def _compute(self, state: State) -> Array:
        return _theils_u_compute(state["confmat"])


class FleissKappa(Metric):
    """Fleiss' kappa inter-rater agreement (nominal/fleiss_kappa.py:29)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, mode: Literal["counts", "probs"] = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ("counts", "probs"):
            raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
        self.mode = mode
        self.add_state("counts", [], dist_reduce_fx="cat")

    def _update(self, state: State, ratings: Array) -> State:
        counts = _fleiss_kappa_update(ratings, self.mode)
        return {"counts": tuple(state["counts"]) + (counts,)}

    def _compute(self, state: State) -> Array:
        return _fleiss_kappa_compute(dim_zero_cat(state["counts"]))
