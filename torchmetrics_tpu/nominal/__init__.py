"""Modular nominal-association metrics (reference: src/torchmetrics/nominal/__init__.py)."""

from torchmetrics_tpu.nominal.nominal import (
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = [
    "CramersV",
    "FleissKappa",
    "PearsonsContingencyCoefficient",
    "TheilsU",
    "TschuprowsT",
]
