"""SSIM / MS-SSIM modular metrics (reference: image/ssim.py:30,220)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.image.ssim import (
    _multiscale_ssim_update,
    _ssim_check_inputs,
    _ssim_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class StructuralSimilarityIndexMeasure(Metric):
    """SSIM; per-image similarity kept as scalar sum (mean reduction) or cat
    state (reference image/ssim.py:30-210).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import StructuralSimilarityIndexMeasure
        >>> metric = StructuralSimilarityIndexMeasure(data_range=1.0)
        >>> img = jnp.arange(256.0).reshape(1, 1, 16, 16) / 256.0
        >>> metric.update(img, img * 0.9)
        >>> round(float(metric.compute()), 4)
        0.9893
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

        if reduction in ("none", None) or return_full_image or return_contrast_sensitivity:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        else:
            self.add_state("similarity", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        if return_full_image or return_contrast_sensitivity:
            self.add_state("image_return", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        preds, target = _ssim_check_inputs(jnp.asarray(preds), jnp.asarray(target))
        out = _ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size,
            self.data_range, self.k1, self.k2,
            self.return_full_image, self.return_contrast_sensitivity,
        )
        new = dict(state)
        if isinstance(out, tuple):
            sim, extra = out
            new["image_return"] = state["image_return"] + (extra,)
        else:
            sim = out
        if isinstance(state["similarity"], tuple):
            new["similarity"] = state["similarity"] + (sim,)
        else:
            new["similarity"] = state["similarity"] + sim.sum()
            new["total"] = state["total"] + sim.shape[0]
        return new

    def _compute(self, state: State):
        if isinstance(state["similarity"], tuple):
            sim = dim_zero_cat(state["similarity"])
            if self.reduction == "elementwise_mean":
                sim = sim.mean()
            elif self.reduction == "sum":
                sim = sim.sum()
            if self.return_full_image or self.return_contrast_sensitivity:
                return sim, dim_zero_cat(state["image_return"])
            return sim
        if self.reduction == "sum":
            return state["similarity"]
        return state["similarity"] / state["total"]


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """MS-SSIM (reference image/ssim.py:220-330)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = "relu",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if not (isinstance(kernel_size, (Sequence, int))):
            raise ValueError("Argument `kernel_size` expected to be an sequence or an int")
        if not isinstance(betas, tuple) or not all(isinstance(b, float) for b in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        if normalize is not None and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.gaussian_kernel = gaussian_kernel
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.betas = betas
        self.normalize = normalize

        if reduction in ("none", None):
            self.add_state("similarity", [], dist_reduce_fx="cat")
        else:
            self.add_state("similarity", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        preds, target = _ssim_check_inputs(jnp.asarray(preds), jnp.asarray(target))
        sim = _multiscale_ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size,
            self.data_range, self.k1, self.k2, self.betas, self.normalize,
        )
        new = dict(state)
        if isinstance(state["similarity"], tuple):
            new["similarity"] = state["similarity"] + (sim,)
        else:
            new["similarity"] = state["similarity"] + sim.sum()
            new["total"] = state["total"] + sim.shape[0]
        return new

    def _compute(self, state: State) -> Array:
        if isinstance(state["similarity"], tuple):
            return dim_zero_cat(state["similarity"])
        if self.reduction == "sum":
            return state["similarity"]
        return state["similarity"] / state["total"]
