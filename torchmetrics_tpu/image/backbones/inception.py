"""InceptionV3 feature extractor in pure JAX (pytorch-fid variant).

Reference: the reference's FID/KID/IS/MiFID embed ``NoTrainInceptionV3``
(/root/reference/src/torchmetrics/image/fid.py:44), a torch-fidelity wrapper
around the torchvision InceptionV3 graph with the pytorch-fid patches
(average pools with ``count_include_pad=False``).  This module implements that
graph as a pure function over a params pytree:

* ``inception_init(key)``          — random params (architecture tests)
* ``load_torch_state_dict(sd)``    — convert a torch InceptionV3 state_dict
  (torchvision/pytorch-fid layout: ``Conv2d_1a_3x3.conv.weight``,
  ``Mixed_5b.branch1x1.bn.running_mean``, ...) into the params pytree,
  folding inference-mode BatchNorm (eps=1e-3) into per-channel scale/bias.
* ``inception_apply(params, x)``   — (B, 3, 299, 299) in [-1, 1] → dict with
  ``pool`` (B, 2048) features and ``logits`` (B, 1008/1000).
* ``preprocess(imgs)``             — uint8 (B, 3, H, W) → bilinear 299x299,
  scaled to [-1, 1] (pytorch-fid input convention).

Weights are never downloaded (zero-egress image); parity with the torch graph
is proven in tests by loading identical random weights into an independently
written torch ``nn.Module`` mirror and asserting feature equality
(tests/unittests/image/test_backbones.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

Params = Dict[str, Any]

_BN_EPS = 1e-3

# (name, in_ch, out_ch, kernel, stride, padding) for the stem
_STEM = (
    ("Conv2d_1a_3x3", 3, 32, (3, 3), 2, (0, 0)),
    ("Conv2d_2a_3x3", 32, 32, (3, 3), 1, (0, 0)),
    ("Conv2d_2b_3x3", 32, 64, (3, 3), 1, (1, 1)),
    ("Conv2d_3b_1x1", 64, 80, (1, 1), 1, (0, 0)),
    ("Conv2d_4a_3x3", 80, 192, (3, 3), 1, (0, 0)),
)


def _conv_spec_a(in_ch: int, pool_features: int):
    return {
        "branch1x1": [(in_ch, 64, (1, 1), 1, (0, 0))],
        "branch5x5_1": [(in_ch, 48, (1, 1), 1, (0, 0))],
        "branch5x5_2": [(48, 64, (5, 5), 1, (2, 2))],
        "branch3x3dbl_1": [(in_ch, 64, (1, 1), 1, (0, 0))],
        "branch3x3dbl_2": [(64, 96, (3, 3), 1, (1, 1))],
        "branch3x3dbl_3": [(96, 96, (3, 3), 1, (1, 1))],
        "branch_pool": [(in_ch, pool_features, (1, 1), 1, (0, 0))],
    }


def _conv_spec_b(in_ch: int):
    return {
        "branch3x3": [(in_ch, 384, (3, 3), 2, (0, 0))],
        "branch3x3dbl_1": [(in_ch, 64, (1, 1), 1, (0, 0))],
        "branch3x3dbl_2": [(64, 96, (3, 3), 1, (1, 1))],
        "branch3x3dbl_3": [(96, 96, (3, 3), 2, (0, 0))],
    }


def _conv_spec_c(in_ch: int, c7: int):
    return {
        "branch1x1": [(in_ch, 192, (1, 1), 1, (0, 0))],
        "branch7x7_1": [(in_ch, c7, (1, 1), 1, (0, 0))],
        "branch7x7_2": [(c7, c7, (1, 7), 1, (0, 3))],
        "branch7x7_3": [(c7, 192, (7, 1), 1, (3, 0))],
        "branch7x7dbl_1": [(in_ch, c7, (1, 1), 1, (0, 0))],
        "branch7x7dbl_2": [(c7, c7, (7, 1), 1, (3, 0))],
        "branch7x7dbl_3": [(c7, c7, (1, 7), 1, (0, 3))],
        "branch7x7dbl_4": [(c7, c7, (7, 1), 1, (3, 0))],
        "branch7x7dbl_5": [(c7, 192, (1, 7), 1, (0, 3))],
        "branch_pool": [(in_ch, 192, (1, 1), 1, (0, 0))],
    }


def _conv_spec_d(in_ch: int):
    return {
        "branch3x3_1": [(in_ch, 192, (1, 1), 1, (0, 0))],
        "branch3x3_2": [(192, 320, (3, 3), 2, (0, 0))],
        "branch7x7x3_1": [(in_ch, 192, (1, 1), 1, (0, 0))],
        "branch7x7x3_2": [(192, 192, (1, 7), 1, (0, 3))],
        "branch7x7x3_3": [(192, 192, (7, 1), 1, (3, 0))],
        "branch7x7x3_4": [(192, 192, (3, 3), 2, (0, 0))],
    }


def _conv_spec_e(in_ch: int):
    return {
        "branch1x1": [(in_ch, 320, (1, 1), 1, (0, 0))],
        "branch3x3_1": [(in_ch, 384, (1, 1), 1, (0, 0))],
        "branch3x3_2a": [(384, 384, (1, 3), 1, (0, 1))],
        "branch3x3_2b": [(384, 384, (3, 1), 1, (1, 0))],
        "branch3x3dbl_1": [(in_ch, 448, (1, 1), 1, (0, 0))],
        "branch3x3dbl_2": [(448, 384, (3, 3), 1, (1, 1))],
        "branch3x3dbl_3a": [(384, 384, (1, 3), 1, (0, 1))],
        "branch3x3dbl_3b": [(384, 384, (3, 1), 1, (1, 0))],
        "branch_pool": [(in_ch, 192, (1, 1), 1, (0, 0))],
    }


_MIXED = (
    ("Mixed_5b", "a", _conv_spec_a(192, 32)),
    ("Mixed_5c", "a", _conv_spec_a(256, 64)),
    ("Mixed_5d", "a", _conv_spec_a(288, 64)),
    ("Mixed_6a", "b", _conv_spec_b(288)),
    ("Mixed_6b", "c", _conv_spec_c(768, 128)),
    ("Mixed_6c", "c", _conv_spec_c(768, 160)),
    ("Mixed_6d", "c", _conv_spec_c(768, 160)),
    ("Mixed_6e", "c", _conv_spec_c(768, 192)),
    ("Mixed_7a", "d", _conv_spec_d(768)),
    ("Mixed_7b", "e", _conv_spec_e(1280)),
    ("Mixed_7c", "e", _conv_spec_e(2048)),
)

NUM_FEATURES = 2048
NUM_LOGITS = 1000


def inception_init(key: Array) -> Params:
    """Random-init params with the exact torch layout (for parity tests)."""
    params: Params = {}

    def conv_init(key, cin, cout, k):
        # He init keeps activation variance alive through the deep ReLU stack
        # so the random-init embedding space is non-degenerate for smoke tests
        fan_in = cin * k[0] * k[1]
        w = jax.random.normal(key, (k[0], k[1], cin, cout)) * np.sqrt(2.0 / fan_in)
        return {"w": w, "scale": jnp.ones((cout,)), "bias": jnp.zeros((cout,))}

    keys = iter(jax.random.split(key, 200))
    for name, cin, cout, k, _, _ in _STEM:
        params[name] = conv_init(next(keys), cin, cout, k)
    for mixed_name, _, branches in _MIXED:
        for bname, convs in branches.items():
            for cin, cout, k, _, _ in convs:
                params[f"{mixed_name}.{bname}"] = conv_init(next(keys), cin, cout, k)
    params["fc"] = {
        "w": jax.random.normal(next(keys), (NUM_FEATURES, NUM_LOGITS)) * 0.01,
        "b": jnp.zeros((NUM_LOGITS,)),
    }
    return params


def load_torch_state_dict(sd: Dict[str, Any]) -> Params:
    """Convert a torchvision/pytorch-fid InceptionV3 ``state_dict`` to params.

    Accepts torch tensors or numpy arrays.  BatchNorm (inference mode,
    eps=1e-3) is folded into per-channel scale/bias:
    ``scale = gamma / sqrt(var + eps)``, ``bias = beta - mean * scale``.
    """

    def arr(v):
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        return jnp.asarray(np.asarray(v), jnp.float32)

    params: Params = {}
    names = [n for n, *_ in _STEM] + [
        f"{mn}.{bn}" for mn, _, brs in _MIXED for bn in brs
    ]
    for name in names:
        w = arr(sd[f"{name}.conv.weight"])  # (O, I, KH, KW)
        gamma = arr(sd[f"{name}.bn.weight"])
        beta = arr(sd[f"{name}.bn.bias"])
        mean = arr(sd[f"{name}.bn.running_mean"])
        var = arr(sd[f"{name}.bn.running_var"])
        scale = gamma / jnp.sqrt(var + _BN_EPS)
        params[name] = {
            "w": jnp.transpose(w, (2, 3, 1, 0)),  # -> HWIO
            "scale": scale,
            "bias": beta - mean * scale,
        }
    if "fc.weight" in sd:
        params["fc"] = {"w": arr(sd["fc.weight"]).T, "b": arr(sd["fc.bias"])}
    else:
        params["fc"] = {
            "w": jnp.zeros((NUM_FEATURES, NUM_LOGITS)),
            "b": jnp.zeros((NUM_LOGITS,)),
        }
    return params


def _conv_bn_relu(x: Array, p: Params, stride: int, padding: Tuple[int, int]) -> Array:
    y = jax.lax.conv_general_dilated(
        x, p["w"], (stride, stride),
        [(padding[0], padding[0]), (padding[1], padding[1])],
        dimension_numbers=("NCHW", "HWIO", "NCHW"),
    )
    return jax.nn.relu(y * p["scale"][None, :, None, None] + p["bias"][None, :, None, None])


def _max_pool(x: Array, window: int = 3, stride: int = 2, pad: int = 0) -> Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max,
        (1, 1, window, window), (1, 1, stride, stride),
        [(0, 0), (0, 0), (pad, pad), (pad, pad)],
    )


def _avg_pool_3x3_s1(x: Array) -> Array:
    """3x3 stride-1 pad-1 average pool with count_include_pad=False.

    The pytorch-fid patch (FIDInceptionA/C/E) — edge windows divide by the
    number of *valid* elements, not 9.
    """
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 1, 3, 3), (1, 1, 1, 1),
        [(0, 0), (0, 0), (1, 1), (1, 1)],
    )
    ones = jnp.ones((1, 1, x.shape[2], x.shape[3]), x.dtype)
    counts = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, (1, 1, 3, 3), (1, 1, 1, 1),
        [(0, 0), (0, 0), (1, 1), (1, 1)],
    )
    return summed / counts


def _run_branch(x: Array, params: Params, mixed: str, names) -> Array:
    for n in names:
        _, _, _, stride, pad = _conv_spec_lookup[mixed][n][0]
        x = _conv_bn_relu(x, params[f"{mixed}.{n}"], stride, pad)
    return x


_conv_spec_lookup = {name: branches for name, _, branches in _MIXED}


def _mixed_a(x: Array, params: Params, name: str) -> Array:
    b1 = _run_branch(x, params, name, ["branch1x1"])
    b5 = _run_branch(x, params, name, ["branch5x5_1", "branch5x5_2"])
    b3 = _run_branch(x, params, name, ["branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"])
    bp = _run_branch(_avg_pool_3x3_s1(x), params, name, ["branch_pool"])
    return jnp.concatenate([b1, b5, b3, bp], axis=1)


def _mixed_b(x: Array, params: Params, name: str) -> Array:
    b3 = _run_branch(x, params, name, ["branch3x3"])
    bd = _run_branch(x, params, name, ["branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"])
    bp = _max_pool(x)
    return jnp.concatenate([b3, bd, bp], axis=1)


def _mixed_c(x: Array, params: Params, name: str) -> Array:
    b1 = _run_branch(x, params, name, ["branch1x1"])
    b7 = _run_branch(x, params, name, ["branch7x7_1", "branch7x7_2", "branch7x7_3"])
    bd = _run_branch(
        x, params, name,
        ["branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3", "branch7x7dbl_4", "branch7x7dbl_5"],
    )
    bp = _run_branch(_avg_pool_3x3_s1(x), params, name, ["branch_pool"])
    return jnp.concatenate([b1, b7, bd, bp], axis=1)


def _mixed_d(x: Array, params: Params, name: str) -> Array:
    b3 = _run_branch(x, params, name, ["branch3x3_1", "branch3x3_2"])
    b7 = _run_branch(x, params, name, ["branch7x7x3_1", "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4"])
    bp = _max_pool(x)
    return jnp.concatenate([b3, b7, bp], axis=1)


def _mixed_e(x: Array, params: Params, name: str, pool: str) -> Array:
    b1 = _run_branch(x, params, name, ["branch1x1"])
    b3 = _run_branch(x, params, name, ["branch3x3_1"])
    b3 = jnp.concatenate(
        [
            _conv_bn_relu(b3, params[f"{name}.branch3x3_2a"], 1, (0, 1)),
            _conv_bn_relu(b3, params[f"{name}.branch3x3_2b"], 1, (1, 0)),
        ],
        axis=1,
    )
    bd = _run_branch(x, params, name, ["branch3x3dbl_1", "branch3x3dbl_2"])
    bd = jnp.concatenate(
        [
            _conv_bn_relu(bd, params[f"{name}.branch3x3dbl_3a"], 1, (0, 1)),
            _conv_bn_relu(bd, params[f"{name}.branch3x3dbl_3b"], 1, (1, 0)),
        ],
        axis=1,
    )
    if pool == "max":
        # pytorch-fid: the LAST InceptionE (FIDInceptionE_2) uses max pooling
        bp = _max_pool(x, window=3, stride=1, pad=1)
    else:
        bp = _avg_pool_3x3_s1(x)
    bp = _run_branch(bp, params, name, ["branch_pool"])
    return jnp.concatenate([b1, b3, bd, bp], axis=1)


def inception_apply(
    params: Params, x: Array, features: Tuple[str, ...] = ("pool", "logits")
) -> Dict[str, Array]:
    """Forward (B, 3, 299, 299) in [-1, 1] → feature dict with keys ``features``.

    Available taps: ``"64"``/``"192"``/``"768"`` — spatially avg-pooled block
    taps at the first max-pool (64 ch), second max-pool (192 ch), and
    Mixed_6e (768 ch), matching the torch-fidelity tap points the reference's
    ``feature`` int selects (reference image/fid.py:320) — plus ``"pool"``
    (B, 2048), ``"logits"`` (B, 1000), and ``"logits_unbiased"`` (fc without
    bias, the reference's IS default, fid.py:137-141).  The forward stops as
    soon as every requested tap is collected, so FID(feature=64) does not pay
    for the Mixed blocks.
    """
    want = set(features)
    out: Dict[str, Array] = {}

    def done() -> bool:
        return want.issubset(out)

    x = _conv_bn_relu(x, params["Conv2d_1a_3x3"], 2, (0, 0))
    x = _conv_bn_relu(x, params["Conv2d_2a_3x3"], 1, (0, 0))
    x = _conv_bn_relu(x, params["Conv2d_2b_3x3"], 1, (1, 1))
    x = _max_pool(x)
    out["64"] = jnp.mean(x, axis=(2, 3))
    if done():
        return {k: out[k] for k in features}
    x = _conv_bn_relu(x, params["Conv2d_3b_1x1"], 1, (0, 0))
    x = _conv_bn_relu(x, params["Conv2d_4a_3x3"], 1, (0, 0))
    x = _max_pool(x)
    out["192"] = jnp.mean(x, axis=(2, 3))
    if done():
        return {k: out[k] for k in features}
    x = _mixed_a(x, params, "Mixed_5b")
    x = _mixed_a(x, params, "Mixed_5c")
    x = _mixed_a(x, params, "Mixed_5d")
    x = _mixed_b(x, params, "Mixed_6a")
    x = _mixed_c(x, params, "Mixed_6b")
    x = _mixed_c(x, params, "Mixed_6c")
    x = _mixed_c(x, params, "Mixed_6d")
    x = _mixed_c(x, params, "Mixed_6e")
    out["768"] = jnp.mean(x, axis=(2, 3))
    if done():
        return {k: out[k] for k in features}
    x = _mixed_d(x, params, "Mixed_7a")
    x = _mixed_e(x, params, "Mixed_7b", pool="avg")
    x = _mixed_e(x, params, "Mixed_7c", pool="max")
    pool = jnp.mean(x, axis=(2, 3))  # adaptive avg pool to 1x1
    out["pool"] = pool
    out["logits_unbiased"] = pool @ params["fc"]["w"]
    out["logits"] = out["logits_unbiased"] + params["fc"]["b"]
    return {k: out[k] for k in features}


def preprocess(imgs: Array, size: int = 299) -> Array:
    """uint8/float (B, 3, H, W) pixel-scale → bilinear 299², scaled to [-1, 1]."""
    x = jnp.asarray(imgs, jnp.float32) / 255.0
    if x.shape[2] != size or x.shape[3] != size:
        x = jax.image.resize(x, (x.shape[0], x.shape[1], size, size), "bilinear")
    return x * 2.0 - 1.0


class InceptionFeatureExtractor:
    """Callable wrapping preprocess + apply; drop-in for the FID family.

    Use ``from_torch_state_dict`` with real pytorch-fid/torchvision weights for
    reference-matching FID; random init still yields a valid (deterministic)
    embedding space for smoke testing.
    """

    num_features = NUM_FEATURES
    _TAP_DIMS = {
        "64": 64, "192": 192, "768": 768, "pool": NUM_FEATURES,
        "logits": NUM_LOGITS, "logits_unbiased": NUM_LOGITS,
    }

    def __init__(
        self,
        params: Optional[Params] = None,
        seed: int = 0,
        return_logits: bool = False,
        feature: str = "pool",
    ) -> None:
        if return_logits:
            feature = "logits"
        if feature not in self._TAP_DIMS:
            raise ValueError(f"Unknown feature tap {feature!r}; expected one of {sorted(self._TAP_DIMS)}")
        self.params = params if params is not None else inception_init(jax.random.PRNGKey(seed))
        self.feature = feature
        self.num_features = self._TAP_DIMS[feature]

    @classmethod
    def from_torch_state_dict(cls, sd: Dict[str, Any], **kwargs: Any) -> "InceptionFeatureExtractor":
        return cls(params=load_torch_state_dict(sd), **kwargs)

    def __call__(self, imgs: Array) -> Array:
        x = jnp.asarray(imgs, jnp.float32)
        # accept [0,1] floats or pixel-scale input
        x = jnp.where(x.max() <= 1.5, x * 255.0, x)
        out = _jit_inception_apply(self.params, preprocess(x), (self.feature,))
        return out[self.feature]


# one shared jitted apply: compile cache survives pickling/cloning of the
# extractor and is shared across FID/KID/IS/MiFID instances; the static
# features tuple prunes the graph to the requested tap depth
_jit_inception_apply = jax.jit(inception_apply, static_argnums=2)
