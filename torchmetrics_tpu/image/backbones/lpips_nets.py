"""VGG16 / AlexNet feature pyramids for LPIPS, in pure JAX.

Reference: the reference LPIPS embeds pretrained torchvision AlexNet/VGG16/
SqueezeNet plus learned linear calibration weights
(/root/reference/src/torchmetrics/functional/image/lpips.py:130-180).  This
module implements the two main backbones as op-list programs over a params
pytree with a ``load_torch_state_dict`` conversion from the torchvision
``features.N.weight`` layout, plus the LPIPS scaling layer.  Weights are not
downloadable here (zero egress); parity of the converted execution is proven
against an independently written torch mirror in
tests/unittests/image/test_backbones.py.

Each backbone yields the canonical LPIPS tap points:

* VGG16:   relu1_2, relu2_2, relu3_3, relu4_3, relu5_3  (64/128/256/512/512 ch)
* AlexNet: relu1..relu5                                  (64/192/384/256/256 ch)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

Params = Dict[str, Any]

# op-list encodings: ("conv", torch_features_index, stride, pad), ("relu",),
# ("maxpool", window, stride), ("tap",) marks an LPIPS feature output
_VGG16_OPS: Tuple[Tuple, ...] = tuple(
    [("conv", 0, 1, 1), ("relu",), ("conv", 2, 1, 1), ("relu",), ("tap",), ("maxpool", 2, 2)]
    + [("conv", 5, 1, 1), ("relu",), ("conv", 7, 1, 1), ("relu",), ("tap",), ("maxpool", 2, 2)]
    + [("conv", 10, 1, 1), ("relu",), ("conv", 12, 1, 1), ("relu",), ("conv", 14, 1, 1), ("relu",), ("tap",), ("maxpool", 2, 2)]
    + [("conv", 17, 1, 1), ("relu",), ("conv", 19, 1, 1), ("relu",), ("conv", 21, 1, 1), ("relu",), ("tap",), ("maxpool", 2, 2)]
    + [("conv", 24, 1, 1), ("relu",), ("conv", 26, 1, 1), ("relu",), ("conv", 28, 1, 1), ("relu",), ("tap",)]
)
# (torch_features_index, cin, cout, kernel, stride, pad)
_VGG16_CONVS = (
    (0, 3, 64, 3, 1, 1), (2, 64, 64, 3, 1, 1),
    (5, 64, 128, 3, 1, 1), (7, 128, 128, 3, 1, 1),
    (10, 128, 256, 3, 1, 1), (12, 256, 256, 3, 1, 1), (14, 256, 256, 3, 1, 1),
    (17, 256, 512, 3, 1, 1), (19, 512, 512, 3, 1, 1), (21, 512, 512, 3, 1, 1),
    (24, 512, 512, 3, 1, 1), (26, 512, 512, 3, 1, 1), (28, 512, 512, 3, 1, 1),
)
VGG16_CHANNELS = (64, 128, 256, 512, 512)

_ALEXNET_OPS: Tuple[Tuple, ...] = (
    ("conv", 0, 4, 2), ("relu",), ("tap",), ("maxpool", 3, 2),
    ("conv", 3, 1, 2), ("relu",), ("tap",), ("maxpool", 3, 2),
    ("conv", 6, 1, 1), ("relu",), ("tap",),
    ("conv", 8, 1, 1), ("relu",), ("tap",),
    ("conv", 10, 1, 1), ("relu",), ("tap",),
)
_ALEXNET_CONVS = (
    (0, 3, 64, 11, 4, 2),
    (3, 64, 192, 5, 1, 2),
    (6, 192, 384, 3, 1, 1),
    (8, 384, 256, 3, 1, 1),
    (10, 256, 256, 3, 1, 1),
)
ALEXNET_CHANNELS = (64, 192, 384, 256, 256)

# SqueezeNet 1.1 (torchvision ``squeezenet1_1().features``): first conv is
# stride-2 unpadded, max pools are 3x2 with ceil_mode=True, and Fire modules
# are squeeze-1x1 → (expand-1x1 ‖ expand-3x3) concat.  LPIPS 'squeeze' taps
# the 7 slice boundaries of the upstream lpips package.
# (torch_features_index, cin, squeeze_ch, expand_ch) — out = 2*expand_ch
_SQUEEZE_FIRES = {
    3: (64, 16, 64), 4: (128, 16, 64),
    6: (128, 32, 128), 7: (256, 32, 128),
    9: (256, 48, 192), 10: (384, 48, 192),
    11: (384, 64, 256), 12: (512, 64, 256),
}
_SQUEEZE_OPS: Tuple[Tuple, ...] = (
    ("conv", 0, 2, 0), ("relu",), ("tap",),
    ("maxpool_ceil", 3, 2), ("fire", 3), ("fire", 4), ("tap",),
    ("maxpool_ceil", 3, 2), ("fire", 6), ("fire", 7), ("tap",),
    ("maxpool_ceil", 3, 2), ("fire", 9), ("tap",),
    ("fire", 10), ("tap",),
    ("fire", 11), ("tap",),
    ("fire", 12), ("tap",),
)
_SQUEEZE_CONVS = ((0, 3, 64, 3, 2, 0),)
SQUEEZE_CHANNELS = (64, 128, 256, 384, 384, 512, 512)

_NETS = {
    "vgg": (_VGG16_OPS, _VGG16_CONVS, VGG16_CHANNELS),
    "vgg16": (_VGG16_OPS, _VGG16_CONVS, VGG16_CHANNELS),
    "alex": (_ALEXNET_OPS, _ALEXNET_CONVS, ALEXNET_CHANNELS),
    "squeeze": (_SQUEEZE_OPS, _SQUEEZE_CONVS, SQUEEZE_CHANNELS),
}

# LPIPS ScalingLayer constants (lpips.py ScalingLayer)
_SHIFT = np.array([-0.030, -0.088, -0.188], np.float32)
_SCALE = np.array([0.458, 0.448, 0.450], np.float32)


def net_init(net: str, key: Array) -> Params:
    """He-init random params in the torch ``features.N`` naming (tests/smoke)."""
    _, convs, _ = _NETS[net]
    n_fire = len(_SQUEEZE_FIRES) if net == "squeeze" else 0
    keys = iter(jax.random.split(key, len(convs) + 3 * n_fire))

    def conv_p(cin, cout, k):
        fan_in = cin * k * k
        return {
            "w": jax.random.normal(next(keys), (k, k, cin, cout)) * np.sqrt(2.0 / fan_in),
            "b": jnp.zeros((cout,)),
        }

    params: Params = {}
    for idx, cin, cout, k, _, _ in convs:
        params[f"features.{idx}"] = conv_p(cin, cout, k)
    if net == "squeeze":
        for idx, (cin, sq, ex) in _SQUEEZE_FIRES.items():
            params[f"features.{idx}.squeeze"] = conv_p(cin, sq, 1)
            params[f"features.{idx}.expand1x1"] = conv_p(sq, ex, 1)
            params[f"features.{idx}.expand3x3"] = conv_p(sq, ex, 3)
    return params


def load_torch_state_dict(net: str, sd: Dict[str, Any]) -> Params:
    """Convert a torchvision vgg16/alexnet ``state_dict`` (``features.N.weight``)."""

    def arr(v):
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        return jnp.asarray(np.asarray(v), jnp.float32)

    def conv_p(prefix):
        w = arr(sd[f"{prefix}.weight"])  # (O, I, KH, KW)
        return {"w": jnp.transpose(w, (2, 3, 1, 0)), "b": arr(sd[f"{prefix}.bias"])}

    _, convs, _ = _NETS[net]
    params: Params = {}
    for idx, *_ in convs:
        params[f"features.{idx}"] = conv_p(f"features.{idx}")
    if net == "squeeze":
        for idx in _SQUEEZE_FIRES:
            for part in ("squeeze", "expand1x1", "expand3x3"):
                params[f"features.{idx}.{part}"] = conv_p(f"features.{idx}.{part}")
    return params


def net_apply(net: str, params: Params, x: Array) -> List[Array]:
    """Run the op list on (B, 3, H, W); returns the LPIPS tap feature maps."""
    ops, _, _ = _NETS[net]
    taps: List[Array] = []
    for op in ops:
        if op[0] == "conv":
            _, idx, stride, pad = op
            p = params[f"features.{idx}"]
            x = jax.lax.conv_general_dilated(
                x, p["w"], (stride, stride), [(pad, pad), (pad, pad)],
                dimension_numbers=("NCHW", "HWIO", "NCHW"),
            ) + p["b"][None, :, None, None]
        elif op[0] == "relu":
            x = jax.nn.relu(x)
        elif op[0] == "maxpool":
            _, window, stride = op
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 1, window, window), (1, 1, stride, stride),
                [(0, 0), (0, 0), (0, 0), (0, 0)],
            )
        elif op[0] == "maxpool_ceil":
            # torch MaxPool2d(ceil_mode=True): pad the end with -inf so the
            # last (partial) window still produces an output element
            _, window, stride = op
            pads = []
            for n in x.shape[2:]:
                out = -(-(n - window) // stride) + 1  # ceil
                pads.append(max(0, (out - 1) * stride + window - n))
            x = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 1, window, window), (1, 1, stride, stride),
                [(0, 0), (0, 0), (0, pads[0]), (0, pads[1])],
            )
        elif op[0] == "fire":
            _, idx = op

            def conv1x1(inp, p):
                return jax.lax.conv_general_dilated(
                    inp, p["w"], (1, 1), [(0, 0), (0, 0)],
                    dimension_numbers=("NCHW", "HWIO", "NCHW"),
                ) + p["b"][None, :, None, None]

            sq = jax.nn.relu(conv1x1(x, params[f"features.{idx}.squeeze"]))
            e1 = jax.nn.relu(conv1x1(sq, params[f"features.{idx}.expand1x1"]))
            p3 = params[f"features.{idx}.expand3x3"]
            e3 = jax.nn.relu(
                jax.lax.conv_general_dilated(
                    sq, p3["w"], (1, 1), [(1, 1), (1, 1)],
                    dimension_numbers=("NCHW", "HWIO", "NCHW"),
                ) + p3["b"][None, :, None, None]
            )
            x = jnp.concatenate([e1, e3], axis=1)
        elif op[0] == "tap":
            taps.append(x)
    return taps


def scaling_layer(x: Array) -> Array:
    """LPIPS input normalization: (x - shift) / scale on [-1, 1] images."""
    return (x - jnp.asarray(_SHIFT)[None, :, None, None]) / jnp.asarray(_SCALE)[None, :, None, None]


class LPIPSBackbone:
    """Callable (B,3,H,W) in [-1,1] → list of feature maps, LPIPS interface.

    ``lin_weights``: per-layer (C,) calibration vectors (the reference's
    learned 1x1 ``lin`` convs).  None → unweighted (all-ones), which is the
    reference's ``lpips=False`` ("baseline") mode.
    """

    def __init__(
        self,
        net: str = "vgg",
        params: Optional[Params] = None,
        lin_weights: Optional[Sequence[Array]] = None,
        seed: int = 0,
    ) -> None:
        if net not in _NETS:
            raise ValueError(f"Unknown LPIPS backbone {net!r}; expected one of {sorted(_NETS)}")
        self.net = net
        self.channels = _NETS[net][2]
        self.params = params if params is not None else net_init(net, jax.random.PRNGKey(seed))
        self.lin_weights = None if lin_weights is None else [jnp.asarray(w) for w in lin_weights]

    @classmethod
    def from_torch_state_dict(cls, net: str, sd: Dict[str, Any], **kwargs: Any) -> "LPIPSBackbone":
        return cls(net=net, params=load_torch_state_dict(net, sd), **kwargs)

    def __call__(self, x: Array) -> List[Array]:
        return _scaled_net_apply(self.net, self.params, jnp.asarray(x, jnp.float32))


def _scaled_net_apply_impl(net: str, params: Params, x: Array) -> List[Array]:
    return net_apply(net, params, scaling_layer(x))


# one shared jitted apply: compilations are cached across backbone instances,
# clones, and unpickles (metrics embedding a backbone must pickle/clone,
# reference metric.py:713-732)
_scaled_net_apply = jax.jit(_scaled_net_apply_impl, static_argnums=0)
