"""Spectral/remote-sensing modular metrics: UQI, SAM, SCC, ERGAS, RASE,
RMSE-SW, D-lambda, D-s, QNR, VIF, TotalVariation.

Reference: image/{uqi.py:29, sam.py:30, scc.py:25, ergas.py:30, rase.py:28,
rmse_sw.py:28, d_lambda.py:29, d_s.py:31, qnr.py:30, vif.py:26, tv.py:24}.
Metrics whose formula is not sum-decomposable keep preds/target cat states,
exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.image.spectral import (
    _rmse_sw_compute,
    error_relative_global_dimensionless_synthesis,
    quality_with_no_reference,
    relative_average_spectral_error,
    spatial_correlation_coefficient,
    spatial_distortion_index,
    spectral_angle_mapper,
    spectral_distortion_index,
    universal_image_quality_index,
    visual_information_fidelity,
)
from torchmetrics_tpu.functional.image.tv import _total_variation_compute, _total_variation_update
from torchmetrics_tpu.utilities.data import dim_zero_cat


class _CatPredsTargetMetric(Metric):
    """Base: accumulate raw preds/target, apply functional at compute."""

    is_differentiable = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        return {
            "preds": state["preds"] + (jnp.asarray(preds),),
            "target": state["target"] + (jnp.asarray(target),),
        }

    def _cat(self, state: State):
        return dim_zero_cat(state["preds"]), dim_zero_cat(state["target"])


class UniversalImageQualityIndex(_CatPredsTargetMetric):
    """UQI (reference image/uqi.py:29)."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return universal_image_quality_index(preds, target, self.kernel_size, self.sigma, self.reduction)


class SpectralAngleMapper(_CatPredsTargetMetric):
    """SAM (reference image/sam.py:30)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reduction = reduction

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return spectral_angle_mapper(preds, target, self.reduction)


class SpatialCorrelationCoefficient(_CatPredsTargetMetric):
    """SCC (reference image/scc.py:25)."""

    higher_is_better = True
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(
        self, hp_filter: Optional[Array] = None, window_size: int = 8, **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        self.hp_filter = hp_filter
        self.window_size = window_size

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return spatial_correlation_coefficient(preds, target, self.hp_filter, self.window_size)


class ErrorRelativeGlobalDimensionlessSynthesis(_CatPredsTargetMetric):
    """ERGAS (reference image/ergas.py:30)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(
        self, ratio: float = 4, reduction: Optional[str] = "elementwise_mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        self.ratio = ratio
        self.reduction = reduction

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return error_relative_global_dimensionless_synthesis(preds, target, self.ratio, self.reduction)


class RelativeAverageSpectralError(_CatPredsTargetMetric):
    """RASE (reference image/rase.py:28)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
        self.window_size = window_size

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return relative_average_spectral_error(preds, target, self.window_size)


class RootMeanSquaredErrorUsingSlidingWindow(_CatPredsTargetMetric):
    """RMSE-SW (reference image/rmse_sw.py:28)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError("Argument `window_size` is expected to be a positive integer.")
        self.window_size = window_size

    def _compute(self, state: State) -> Array:
        from torchmetrics_tpu.functional.image.spectral import _rmse_sw_update

        preds, target = self._cat(state)
        rmse_val_sum, rmse_map, total = _rmse_sw_update(preds, target, self.window_size, None, None, None)
        rmse, _ = _rmse_sw_compute(rmse_val_sum, rmse_map, total)
        return rmse


class SpectralDistortionIndex(_CatPredsTargetMetric):
    """D-lambda (reference image/d_lambda.py:29)."""

    higher_is_better = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        self.reduction = reduction

    def _compute(self, state: State) -> Array:
        preds, target = self._cat(state)
        return spectral_distortion_index(preds, target, self.p, self.reduction)


class SpatialDistortionIndex(Metric):
    """D-s (reference image/d_s.py:31); update takes dict target with ms/pan."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        norm_order: int = 1,
        window_size: int = 7,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.norm_order = norm_order
        self.window_size = window_size
        self.reduction = reduction
        for name in ("preds", "ms", "pan", "pan_lr"):
            self.add_state(name, [], dist_reduce_fx="cat")

    def _update(self, state: State, preds: Array, target: dict) -> State:
        if "ms" not in target or "pan" not in target:
            raise ValueError(f"Expected `target` to have keys ('ms', 'pan'). Got {list(target)}.")
        new = dict(state)
        new["preds"] = state["preds"] + (jnp.asarray(preds),)
        new["ms"] = state["ms"] + (jnp.asarray(target["ms"]),)
        new["pan"] = state["pan"] + (jnp.asarray(target["pan"]),)
        if "pan_lr" in target:
            new["pan_lr"] = state["pan_lr"] + (jnp.asarray(target["pan_lr"]),)
        return new

    def _compute(self, state: State) -> Array:
        preds = dim_zero_cat(state["preds"])
        ms = dim_zero_cat(state["ms"])
        pan = dim_zero_cat(state["pan"])
        pan_lr = dim_zero_cat(state["pan_lr"]) if state["pan_lr"] else None
        return spatial_distortion_index(
            preds, ms, pan, pan_lr, self.norm_order, self.window_size, self.reduction
        )


class QualityWithNoReference(SpatialDistortionIndex):
    """QNR (reference image/qnr.py:30)."""

    higher_is_better = True

    def __init__(
        self,
        alpha: float = 1.0,
        beta: float = 1.0,
        norm_order: int = 1,
        window_size: int = 7,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(norm_order=norm_order, window_size=window_size, reduction=reduction, **kwargs)
        if not isinstance(alpha, (int, float)) or alpha < 0:
            raise ValueError(f"Expected `alpha` to be a non-negative real number. Got alpha: {alpha}.")
        if not isinstance(beta, (int, float)) or beta < 0:
            raise ValueError(f"Expected `beta` to be a non-negative real number. Got beta: {beta}.")
        self.alpha = alpha
        self.beta = beta

    def _compute(self, state: State) -> Array:
        preds = dim_zero_cat(state["preds"])
        ms = dim_zero_cat(state["ms"])
        pan = dim_zero_cat(state["pan"])
        pan_lr = dim_zero_cat(state["pan_lr"]) if state["pan_lr"] else None
        return quality_with_no_reference(
            preds, ms, pan, pan_lr, self.alpha, self.beta, self.norm_order, self.window_size, self.reduction
        )


class VisualInformationFidelity(Metric):
    """VIF-p; sum-decomposable over images (reference image/vif.py:26)."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, sigma_n_sq: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(sigma_n_sq, (int, float)) or sigma_n_sq < 0:
            raise ValueError(f"Argument `sigma_n_sq` is expected to be a positive float or int, but got {sigma_n_sq}")
        self.sigma_n_sq = sigma_n_sq
        self.add_state("vif_score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        score = visual_information_fidelity(preds, target, self.sigma_n_sq)
        return {
            "vif_score": state["vif_score"] + score * preds.shape[0],
            "total": state["total"] + preds.shape[0],
        }

    def _compute(self, state: State) -> Array:
        return state["vif_score"] / state["total"]


class TotalVariation(Metric):
    """TV (reference image/tv.py:24).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import TotalVariation
        >>> metric = TotalVariation()
        >>> img = jnp.arange(48.0).reshape(1, 3, 4, 4) / 48.0
        >>> metric.update(img)
        >>> round(float(metric.compute()), 4)
        3.75
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        if reduction in (None, "none"):
            self.add_state("score_list", [], dist_reduce_fx="cat")
        else:
            self.add_state("score", jnp.zeros(()), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, img: Array) -> State:
        score, num = _total_variation_update(jnp.asarray(img))
        if self.reduction in (None, "none"):
            return {"score_list": state["score_list"] + (score,)}
        return {
            "score": state["score"] + score.sum(),
            "num_elements": state["num_elements"] + num,
        }

    def _compute(self, state: State) -> Array:
        if self.reduction in (None, "none"):
            return dim_zero_cat(state["score_list"])
        return _total_variation_compute(state["score"], state["num_elements"], self.reduction)
