"""PSNR / PSNR-B modular metrics (reference: image/psnr.py:31, image/psnrb.py:29)."""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.image.psnr import (
    _psnr_compute,
    _psnr_update,
    _psnrb_compute,
    _psnrb_update,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class PeakSignalNoiseRatio(Metric):
    """PSNR; scalar sum states when ``dim`` is None, cat states otherwise;
    data range inferred via min/max states when not given (reference
    image/psnr.py:31-150).
    Example::

        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.image import PeakSignalNoiseRatio
        >>> metric = PeakSignalNoiseRatio(data_range=1.0)
        >>> preds = jnp.full((1, 3, 8, 8), 0.4)
        >>> target = jnp.full((1, 3, 8, 8), 0.5)
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()), 4)
        20.0
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            from torchmetrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
        self.base = base
        self.reduction = reduction
        self.dim = (dim,) if isinstance(dim, int) else dim
        # public so the clamp bounds fingerprint: data_range=(0, 1) and (1, 2)
        # share self.data_range == 1.0 but compile different clip constants
        self.clamp_range: Optional[Tuple[float, float]] = None

        if dim is None:
            self.add_state("sum_squared_error", jnp.zeros(()), dist_reduce_fx="sum", value_range=(0.0, float("inf")))
            # total counts *pixels*, not samples: int32 is exact to 2**31
            # (~11M 178x178 images) vs float32's 2**24 stagnation cliff, and
            # int64 is gated behind jax x64 mode.  The residual int32 horizon
            # is below a 1e9-sample budget by construction — documented here
            # rather than widened further.
            self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum", value_range=(0.0, float("inf")))  # tmt: ignore[TMT014] -- pixel-count accumulator: int32 exact to 2**31 px; int64 needs x64 mode
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")

        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", jnp.asarray(jnp.inf), dist_reduce_fx="min")
            self.add_state("max_target", jnp.asarray(-jnp.inf), dist_reduce_fx="max")
        elif isinstance(data_range, tuple):
            self.data_range = jnp.asarray(data_range[1] - data_range[0])
            self.clamp_range = (float(data_range[0]), float(data_range[1]))
        else:
            self.data_range = jnp.asarray(float(data_range))

    def _update(self, state: State, preds: Array, target: Array) -> State:
        if self.clamp_range is not None:
            preds = jnp.clip(preds, self.clamp_range[0], self.clamp_range[1])
            target = jnp.clip(target, self.clamp_range[0], self.clamp_range[1])
        sse, n = _psnr_update(preds, target, dim=self.dim)
        new = dict(state)
        if self.dim is None:
            new["sum_squared_error"] = state["sum_squared_error"] + sse
            new["total"] = state["total"] + jnp.asarray(n, state["total"].dtype)
            if self.data_range is None:
                # range inferred from target only (reference psnr.py:145)
                new["min_target"] = jnp.minimum(state["min_target"], target.min())
                new["max_target"] = jnp.maximum(state["max_target"], target.max())
        else:
            new["sum_squared_error"] = state["sum_squared_error"] + (sse.ravel(),)
            new["total"] = state["total"] + (n.ravel(),)
        return new

    def _compute(self, state: State) -> Array:
        if self.data_range is not None:
            rng = self.data_range
        else:
            rng = state["max_target"] - state["min_target"]
        if self.dim is None:
            sse, total = state["sum_squared_error"], state["total"]
        else:
            sse = dim_zero_cat(state["sum_squared_error"])
            total = dim_zero_cat(state["total"])
        return _psnr_compute(sse, total, rng, base=self.base, reduction=self.reduction)


class PeakSignalNoiseRatioWithBlockedEffect(Metric):
    """PSNR-B (reference image/psnrb.py:29-110); grayscale only."""

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, block_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size
        self.add_state("sum_squared_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("bef", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("data_range", jnp.zeros(()), dist_reduce_fx="max")

    def _update(self, state: State, preds: Array, target: Array) -> State:
        sse, bef, n = _psnrb_update(preds, target, block_size=self.block_size)
        return {
            "sum_squared_error": state["sum_squared_error"] + sse,
            "total": state["total"] + n,
            "bef": state["bef"] + bef,
            "data_range": jnp.maximum(state["data_range"], target.max() - target.min()),
        }

    def _compute(self, state: State) -> Array:
        return _psnrb_compute(
            state["sum_squared_error"], state["bef"], state["total"], state["data_range"]
        )
