"""Image metrics (reference: src/torchmetrics/image/__init__.py)."""

from torchmetrics_tpu.image.psnr import (
    PeakSignalNoiseRatio,
    PeakSignalNoiseRatioWithBlockedEffect,
)
from torchmetrics_tpu.image.spectral import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    QualityWithNoReference,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpatialCorrelationCoefficient,
    SpatialDistortionIndex,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)
from torchmetrics_tpu.image.ssim import (
    MultiScaleStructuralSimilarityIndexMeasure,
    StructuralSimilarityIndexMeasure,
)
from torchmetrics_tpu.image.generative import (
    DeterministicFeatureExtractor,
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    LearnedPerceptualImagePatchSimilarity,
    MemorizationInformedFrechetInceptionDistance,
    PerceptualPathLength,
)

__all__ = [
    "DeterministicFeatureExtractor",
    "FrechetInceptionDistance",
    "InceptionScore",
    "KernelInceptionDistance",
    "LearnedPerceptualImagePatchSimilarity",
    "MemorizationInformedFrechetInceptionDistance",
    "PerceptualPathLength",
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "QualityWithNoReference",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpatialCorrelationCoefficient",
    "SpatialDistortionIndex",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
