"""Generative-model image metrics: FID, KID, IS, MiFID, LPIPS, PPL.

Reference: image/{fid.py:182, kid.py:70, inception.py:34, mifid.py:66,
lpip.py:40, perceptual_path_length.py:32}.  The reference embeds a downloaded
``NoTrainInceptionV3`` inside each metric (fid.py:44); here every default
``feature`` choice (64/192/768/2048/logits) resolves the real JAX
InceptionV3 port (image/backbones/inception.py) — weights load from
``TORCHMETRICS_TPU_INCEPTION_WEIGHTS`` when available, random-init otherwise
(same graph, conversion parity-tested).  A custom extractor callable
((B,C,H,W) images → (B,D) features) can be passed explicitly;
``DeterministicFeatureExtractor`` remains available as an explicit opt-in
stand-in for hermetic smoke tests.  Statistics, states, and sync semantics
mirror the reference exactly (sum-reduced feature sums + covariance sums for
FID/MiFID, cat feature lists for KID/IS).

Example::

    >>> import jax.numpy as jnp
    >>> import numpy as np
    >>> from torchmetrics_tpu.image import FrechetInceptionDistance
    >>> fid = FrechetInceptionDistance(feature=64)
    >>> rng = np.random.default_rng(0)
    >>> imgs = jnp.asarray(rng.integers(0, 255, (4, 3, 32, 32)), jnp.uint8)
    >>> fid.update(imgs, real=True)
    >>> fid.update(imgs, real=False)
    >>> round(float(fid.compute()), 4)  # identical distributions -> 0
    -0.0
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.functional.image.generative import (
    _compute_fid_np,
    _mean_cov,
    _mifid_compute,
    inception_score_from_logits,
    kid_from_features,
)
from torchmetrics_tpu.functional.image.lpips import (
    _default_net,
    learned_perceptual_image_patch_similarity,
)
from torchmetrics_tpu.utilities.data import dim_zero_cat


class DeterministicFeatureExtractor:
    """Seeded random conv encoder: (B, C, H, W) uint8/float → (B, dim) features.

    Stands in for the reference's pretrained InceptionV3; a Flax port with
    converted weights plugs in through the same callable interface.
    """

    def __init__(self, dim: int = 64, seed: int = 0, num_layers: int = 3) -> None:
        self.num_features = dim
        key = jax.random.PRNGKey(seed)
        self.kernels = []
        in_ch = 3
        ch = 16
        for _ in range(num_layers):
            key, sub = jax.random.split(key)
            self.kernels.append(jax.random.normal(sub, (ch, in_ch, 3, 3)) / jnp.sqrt(9.0 * in_ch))
            in_ch, ch = ch, ch * 2
        key, sub = jax.random.split(key)
        self.proj = jax.random.normal(sub, (in_ch, dim)) / jnp.sqrt(float(in_ch))

    def __call__(self, imgs: Array) -> Array:
        x = jnp.asarray(imgs, jnp.float32)
        # trace-safe range normalization: uint8-scale inputs come down to [0,1]
        x = jnp.where(x.max() > 1.5, x / 255.0, x)
        if x.shape[1] == 1:
            x = jnp.tile(x, (1, 3, 1, 1))
        for w in self.kernels:
            x = jax.lax.conv_general_dilated(
                x, w, window_strides=(2, 2), padding="SAME",
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
            )
            x = jax.nn.relu(x)
        pooled = x.mean(axis=(2, 3))
        return pooled @ self.proj


def _maybe_to_uint8(imgs: Array, normalize: bool) -> Array:
    """[0,1] floats → uint8 pixel scale when ``normalize`` (reference fid.py:update)."""
    imgs = jnp.asarray(imgs)
    if normalize and jnp.issubdtype(imgs.dtype, jnp.floating):
        return (imgs * 255).astype(jnp.uint8)
    return imgs


class _RealFeaturesResetMixin:
    """Honors ``reset_real_features=False`` for cat-state metrics (reference
    kid.py/mifid.py reset overrides)."""

    def reset(self) -> None:
        if not self.reset_real_features:
            saved = self._state["real_features"]
            super().reset()
            self._state["real_features"] = saved
        else:
            super().reset()


def _load_inception(feature: str = "pool", weights_path: Optional[str] = None):
    """Real JAX InceptionV3 (pytorch-fid graph, image/backbones/inception.py).

    Weights: a torch/numpy state_dict at ``weights_path`` or the
    ``TORCHMETRICS_TPU_INCEPTION_WEIGHTS`` env var (zero-egress image, so
    nothing is downloaded); random-init otherwise — the architecture is still
    the real one and the conversion path is parity-tested.
    """
    import os

    from torchmetrics_tpu.image.backbones.inception import InceptionFeatureExtractor

    weights_path = weights_path or os.environ.get("TORCHMETRICS_TPU_INCEPTION_WEIGHTS")
    if weights_path:
        if weights_path.endswith(".npz"):
            import numpy as _np

            sd = dict(_np.load(weights_path))
        else:
            import torch as _torch

            sd = _torch.load(weights_path, map_location="cpu")
        return InceptionFeatureExtractor.from_torch_state_dict(sd, feature=feature)
    return InceptionFeatureExtractor(feature=feature)


def _resolve_feature_extractor(
    feature: Union[int, str, Callable, None], default_dim: int = 2048
) -> Tuple[Callable, int]:
    if feature is None:
        feature = default_dim
    if isinstance(feature, str):
        # reference InceptionScore accepts "logits_unbiased" (inception.py:34);
        # "inception" selects the pooled 2048-d features explicitly
        if feature == "inception":
            net = _load_inception("pool")
            return net, net.num_features
        if feature in ("logits", "logits_unbiased"):
            from torchmetrics_tpu.image.backbones.inception import NUM_LOGITS

            # "logits_unbiased" omits the fc bias (reference fid.py:137-141)
            return _load_inception(feature), NUM_LOGITS
        raise ValueError(f"Got unknown input to argument `feature`: {feature!r}")
    if isinstance(feature, int):
        # every valid int selects a real InceptionV3 tap (64/192: max-pool
        # blocks, 768: Mixed_6e, 2048: final pool) — same choices and error
        # as the reference (fid.py:320-323); no stand-in is reachable here
        valid_int_input = (64, 192, 768, 2048)
        if feature not in valid_int_input:
            raise ValueError(
                f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
            )
        tap = "pool" if feature == 2048 else str(feature)
        return _load_inception(tap), feature
    if callable(feature):
        dim = getattr(feature, "num_features", None)
        if dim is None:
            probe = feature(jnp.zeros((1, 3, 32, 32)))
            dim = probe.shape[-1]
        return feature, int(dim)
    raise TypeError(f"Got unknown input to argument `feature`: {feature}")


class FrechetInceptionDistance(Metric):
    """FID with streaming mean/covariance sum states (reference image/fid.py:182-400)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[int, Callable, None] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, num_features = _resolve_feature_extractor(feature)
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.reset_real_features = reset_real_features
        self.normalize = normalize
        self.num_features = num_features

        # device states stay float32 (x64 is globally disabled under jit);
        # the final mean/cov/Fréchet math runs in host float64 at compute
        self.add_state("real_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", jnp.zeros((num_features, num_features)), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", jnp.zeros(num_features), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", jnp.zeros((num_features, num_features)), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def _featurize(self, imgs: Array) -> Array:
        return jnp.asarray(self.inception(_maybe_to_uint8(imgs, self.normalize)), jnp.float32)

    def _update(self, state: State, imgs: Array, real: bool) -> State:
        features = self._featurize(imgs)
        prefix = "real" if real else "fake"
        new = dict(state)
        new[f"{prefix}_features_sum"] = state[f"{prefix}_features_sum"] + features.sum(axis=0)
        new[f"{prefix}_features_cov_sum"] = state[f"{prefix}_features_cov_sum"] + features.T @ features
        new[f"{prefix}_features_num_samples"] = state[f"{prefix}_features_num_samples"] + features.shape[0]
        return new

    def _compute(self, state: State) -> Array:
        import numpy as np

        if float(state["real_features_num_samples"]) < 2 or float(state["fake_features_num_samples"]) < 2:  # tmt: ignore[TMT003, TMT004, TMT018] -- host-side FID compute: sample-count sanity check before np sqrtm path; vmap-unliftable by design (fleet certificate classifies FID unliftable)
            raise RuntimeError("More than one sample is required for both the real and fake distributed to compute FID")
        mu_real, cov_real = _mean_cov(
            np.asarray(state["real_features_sum"], np.float64),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
            np.asarray(state["real_features_cov_sum"], np.float64),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
            float(state["real_features_num_samples"]),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
        )
        mu_fake, cov_fake = _mean_cov(
            np.asarray(state["fake_features_sum"], np.float64),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
            np.asarray(state["fake_features_cov_sum"], np.float64),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
            float(state["fake_features_num_samples"]),  # tmt: ignore[TMT003] -- host-side FID compute: covariance math in np.float64 on host
        )
        return jnp.asarray(_compute_fid_np(mu_real, cov_real, mu_fake, cov_fake), jnp.float32)

    def reset(self) -> None:
        """Optionally preserve real statistics (reference fid.py:395-410)."""
        if not self.reset_real_features:
            saved = {
                k: self._state[k]
                for k in ("real_features_sum", "real_features_cov_sum", "real_features_num_samples")
            }
            super().reset()
            self._state.update(saved)
        else:
            super().reset()


class MemorizationInformedFrechetInceptionDistance(_RealFeaturesResetMixin, Metric):
    """MiFID (reference image/mifid.py:66-260); keeps raw feature cat states."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[int, Callable, None] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        cosine_distance_eps: float = 0.1,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, self.num_features = _resolve_feature_extractor(feature)
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        if not (isinstance(cosine_distance_eps, float) and 1 >= cosine_distance_eps > 0):
            raise ValueError("Argument `cosine_distance_eps` expected to be a float greater than 0 and less than 1")
        self.reset_real_features = reset_real_features
        self.normalize = normalize
        self.cosine_distance_eps = cosine_distance_eps

        self.add_state("real_features", [], dist_reduce_fx="cat")
        self.add_state("fake_features", [], dist_reduce_fx="cat")

    def _update(self, state: State, imgs: Array, real: bool) -> State:
        features = jnp.asarray(self.inception(_maybe_to_uint8(imgs, self.normalize)), jnp.float32)
        key = "real_features" if real else "fake_features"
        return {**state, key: state[key] + (features,)}

    def _compute(self, state: State) -> Array:
        # double precision on host: the reference's fid>1e-8 zero-gate
        # (mifid.py:62) is meaningless at float32 noise levels
        import numpy as np

        real = np.asarray(dim_zero_cat(state["real_features"]), np.float64)  # tmt: ignore[TMT003] -- host-side MiFID compute in np.float64 on host
        fake = np.asarray(dim_zero_cat(state["fake_features"]), np.float64)  # tmt: ignore[TMT003] -- host-side MiFID compute in np.float64 on host
        return _mifid_compute(
            real.mean(axis=0), np.cov(real.T), real,
            fake.mean(axis=0), np.cov(fake.T), fake,
            self.cosine_distance_eps,
        ).astype(jnp.float32)


class KernelInceptionDistance(_RealFeaturesResetMixin, Metric):
    """KID mean/std over feature subsets (reference image/kid.py:70-260)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[int, Callable, None] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, self.num_features = _resolve_feature_extractor(feature)
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.subsets = subsets
        self.subset_size = subset_size
        self.degree = degree
        self.gamma = gamma
        self.coef = coef
        self.reset_real_features = reset_real_features
        self.normalize = normalize

        self.add_state("real_features", [], dist_reduce_fx="cat")
        self.add_state("fake_features", [], dist_reduce_fx="cat")

    def _update(self, state: State, imgs: Array, real: bool) -> State:
        features = jnp.asarray(self.inception(_maybe_to_uint8(imgs, self.normalize)))
        key = "real_features" if real else "fake_features"
        return {**state, key: state[key] + (features,)}

    def _compute(self, state: State) -> Tuple[Array, Array]:
        real = dim_zero_cat(state["real_features"])
        fake = dim_zero_cat(state["fake_features"])
        return kid_from_features(
            real, fake, self.subsets, self.subset_size, self.degree, self.gamma, self.coef
        )


class InceptionScore(Metric):
    """IS mean/std over splits (reference image/inception.py:34-200)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        feature: Union[int, str, Callable, None] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.inception, self.num_features = _resolve_feature_extractor(feature)
        if not (isinstance(splits, int) and splits > 0):
            raise ValueError("Argument `splits` expected to be integer larger than 0")
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.splits = splits
        self.normalize = normalize
        self.add_state("features", [], dist_reduce_fx="cat")

    def _update(self, state: State, imgs: Array) -> State:
        features = jnp.asarray(self.inception(_maybe_to_uint8(imgs, self.normalize)))
        return {**state, "features": state["features"] + (features,)}

    def _compute(self, state: State) -> Tuple[Array, Array]:
        logits = dim_zero_cat(state["features"])
        return inception_score_from_logits(logits, self.splits)


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference image/lpip.py:40-180); backbone pluggable via ``net``."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        net: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if net_type not in ("alex", "vgg", "squeeze"):
            raise ValueError(f"Argument `net_type` must be one of 'alex', 'vgg', 'squeeze', but got {net_type}")
        if reduction not in ("mean", "sum"):
            raise ValueError(f"Argument `reduction` must be one of 'mean', 'sum', but got {reduction}")
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` should be a bool but got {normalize}")
        self.net_type = net_type
        self.reduction = reduction
        self.normalize = normalize
        # Resolve the same default backbone as the functional path so the
        # modular class and `learned_perceptual_image_patch_similarity` agree
        # (reference image/lpip.py:40 delegates to the identical _lpips_* path).
        self.net = net if net is not None else _default_net(net_type)

        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def _update(self, state: State, img1: Array, img2: Array) -> State:
        loss = learned_perceptual_image_patch_similarity(
            img1, img2, self.net_type, reduction="sum", normalize=self.normalize, net=self.net
        )
        return {
            "sum_scores": state["sum_scores"] + loss,
            "total": state["total"] + jnp.asarray(img1.shape[0], jnp.float32),
        }

    def _compute(self, state: State) -> Array:
        if self.reduction == "mean":
            return state["sum_scores"] / state["total"]
        return state["sum_scores"]


class PerceptualPathLength(Metric):
    """PPL (reference image/perceptual_path_length.py:32-200).

    The generator must expose ``sample(key, num_samples) -> latents`` and be
    callable ``generator(z) -> images in [-1, 1]`` (the reference requires the
    same duck-typed interface, perceptual_path_length.py:_validate_generator).
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        num_samples: int = 10_000,
        conditional: bool = False,
        batch_size: int = 64,
        interpolation_method: str = "lerp",
        epsilon: float = 1e-4,
        resize: Optional[int] = 64,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        sim_net: Optional[Callable] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_samples, int) and num_samples > 0):
            raise ValueError(f"Argument `num_samples` must be a positive integer, but got {num_samples}")
        if interpolation_method not in ("lerp", "slerp_any", "slerp_unit"):
            raise ValueError(
                f"Argument `interpolation_method` must be one of 'lerp', 'slerp_any', 'slerp_unit', got {interpolation_method}"
            )
        if not (isinstance(epsilon, float) and epsilon > 0):
            raise ValueError(f"Argument `epsilon` must be a positive float, but got {epsilon}")
        for name, val in (("lower_discard", lower_discard), ("upper_discard", upper_discard)):
            if val is not None and not (isinstance(val, float) and 0 <= val <= 1):
                raise ValueError(f"Argument `{name}` must be a float between 0 and 1 or None, but got {val}")
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.resize = resize
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        # Reference PPL measures distances with a vgg-backboned LPIPS
        # (reference image/perceptual_path_length.py:150); resolve the same
        # default backbone as the LPIPS paths instead of a stand-in.
        self.sim_net = sim_net if sim_net is not None else _default_net("vgg")
        self.add_state("distances", [], dist_reduce_fx="cat")

    @staticmethod
    def _interpolate(z1: Array, z2: Array, t: Array, method: str) -> Array:
        if method == "lerp":
            return z1 + (z2 - z1) * t
        # spherical interpolation
        z1n = z1 / jnp.linalg.norm(z1, axis=-1, keepdims=True)
        z2n = z2 / jnp.linalg.norm(z2, axis=-1, keepdims=True)
        omega = jnp.arccos(jnp.clip((z1n * z2n).sum(-1, keepdims=True), -1, 1))
        so = jnp.sin(omega)
        out = jnp.sin((1.0 - t) * omega) / so * z1 + jnp.sin(t * omega) / so * z2
        if method == "slerp_unit":
            out = out / jnp.linalg.norm(out, axis=-1, keepdims=True)
        return out

    def _update(self, state: State, generator: Any) -> State:
        if not hasattr(generator, "sample") or not callable(generator):
            raise NotImplementedError(
                "The generator must be callable and have a `sample` method (key, num_samples) -> latents."
            )
        if self.conditional and not hasattr(generator, "num_classes"):
            raise AttributeError(
                "Conditional PPL requires the generator to expose a `num_classes` attribute "
                "and accept `generator(z, labels)` (reference perceptual_path_length.py:_validate_generator)."
            )
        from torchmetrics_tpu.functional.image.lpips import _lpips_from_features

        key = jax.random.PRNGKey(int(state.get("_n", 0)))  # tmt: ignore[TMT003] -- host-side sampling loop: PRNG seed derives from a host int
        distances = []
        done = 0
        while done < self.num_samples:
            n = min(self.batch_size, self.num_samples - done)
            key, k1, k2, kt, kl = jax.random.split(key, 5)
            z1 = generator.sample(k1, n)
            z2 = generator.sample(k2, n)
            t = jax.random.uniform(kt, (n, 1))
            za = self._interpolate(z1, z2, t, self.interpolation_method)
            zb = self._interpolate(z1, z2, t + self.epsilon, self.interpolation_method)
            if self.conditional:
                labels = jax.random.randint(kl, (n,), 0, int(generator.num_classes))  # tmt: ignore[TMT003] -- host-side sampling loop: label count is host config
                img_a = jnp.asarray(generator(za, labels))
                img_b = jnp.asarray(generator(zb, labels))
            else:
                img_a = jnp.asarray(generator(za))
                img_b = jnp.asarray(generator(zb))
            if self.resize is not None:
                img_a = jax.image.resize(img_a, (*img_a.shape[:2], self.resize, self.resize), "bilinear")
                img_b = jax.image.resize(img_b, (*img_b.shape[:2], self.resize, self.resize), "bilinear")
            d = _lpips_from_features(
                self.sim_net(img_a), self.sim_net(img_b), getattr(self.sim_net, "lin_weights", None)
            ) / self.epsilon**2
            distances.append(d)
            done += n
        return {"distances": state["distances"] + (jnp.concatenate(distances),)}

    def _compute(self, state: State) -> Tuple[Array, Array, Array]:
        import numpy as np

        distances = np.asarray(dim_zero_cat(state["distances"]))  # tmt: ignore[TMT003] -- host-side compute: np.quantile discard thresholds
        lower = np.quantile(distances, self.lower_discard) if self.lower_discard is not None else distances.min()
        upper = np.quantile(distances, self.upper_discard) if self.upper_discard is not None else distances.max()
        kept = distances[(distances >= lower) & (distances <= upper)]
        return (
            jnp.asarray(kept.mean(), jnp.float32),
            jnp.asarray(kept.std(), jnp.float32),
            jnp.asarray(kept, jnp.float32),
        )
