"""Gather-plane observability: live cat-state attribution, pod-scale
projection, and an actuating :class:`GatherAdvisor`.

The psum family is fully instrumented (per-bucket measured timing, ring and
two-stage byte models, residuals, ShardingAdvisor); this module does the same
for the *gather family* — the cat/reservoir/structural leaves that
``core.reductions.sync_leaf`` lowers to padded all-gathers and that grow
per step instead of combining.  Three layers:

1. **Live cat-state growth accounting** — every
   :meth:`~torchmetrics_tpu.parallel.ragged.DeferredRaggedSync.update_for`
   step sizes the gather-family leaves it appended (:func:`cat_growth_rows`,
   unpadded item bytes summed over the local mesh — the same whole-update
   accounting ``bench.py``'s ``cat_state_bytes_per_step`` uses) and folds
   them into the telemetry registry: per-leaf elements/bytes per step, an
   exponentially-weighted growth rate, and the cat-state high-watermark.
   The deferred gather itself is timed block-until-ready at the host
   boundary and lands in per-bucket ``measured_us`` rows
   (``registry.record_measured_gather``) exactly the way coalesced psum
   buckets already do, with the flat ``(n-1)*B`` and granule-tiled
   (``utilities.benchmark.tiled_allgather_bytes``) byte models alongside so
   exporters can show the model-vs-measured residual.
2. **Pod-scale projection** — :func:`project_gather_bytes` extrapolates the
   live per-step attribution to 8/16/64-chip meshes with the flat all-gather
   model.  This is how the bench reproduces BENCH_r05's mAP figure of
   5,402,880 bytes/chip/step at 64 chips from *live* data (the gather
   family's counterpart of the ShardingAdvisor's 33,570,840 psum-byte
   reproduction).
3. **Advice and actuation** — :class:`GatherAdvisor` ranks cat-state
   consumers by projected pod-scale bytes and models both escape hatches:
   the two-stage ICI-gather→DCN-exchange route (cross-host bytes scale with
   hosts, not chips — ``utilities.benchmark.two_stage_gather_bytes``, after
   arxiv 2204.06514) and the sketch-mode cut (a fixed-shape state rides the
   psum family instead; where the sketch layer already ships one — AUROC's
   ``thresholds=N`` binned mode, mAP's ``approx="sketch"`` histograms, the
   text metrics' ``approx="reservoir"`` corpus sample — the advisor quotes
   it by name).  Every ``advise()`` lands in a ledger as a
   ``kind: "gather_advice"`` row, exportable through the JSONL front door;
   :meth:`GatherAdvisor.recommend` with ``apply=True`` promotes the advice
   to an audited commit (observe→candidate→trial→committed, mirroring the
   ShardingAdvisor): sketch-first candidates convert via
   ``metric.set_approx``, two-stage candidates flip the accumulator route,
   every transition lands as a ``kind: "gather_decision"`` row with a
   rollback token behind it, ``guardrail_sink()`` wires health alerts to
   veto or roll back, and ``retrace_report()`` audits the compile-cache
   delta against the commit's expected new keys.

Everything is double-gated: :func:`enable_gather_telemetry` arms the plane,
but nothing records until ``observability.enable()`` is also on (mirroring
the memory and accuracy planes).  Arming adds **zero retraces and zero cache
entries**: growth sizing reads host-side shapes the update already computed,
and the measured gather timing wraps a collective that already runs —
proven by the jaxpr bit-identity and ``cache_stats`` delta tests in
``test_gathers.py``.

Quick tour::

    from torchmetrics_tpu import observability as obs
    from torchmetrics_tpu.observability import gathers

    obs.enable()
    gathers.enable_gather_telemetry()     # or TM_TPU_GATHER_TELEMETRY=1
    acc = DeferredRaggedSync(map_metric, mesh=mesh)
    ...                                   # update steps are sized live
    map_metric.telemetry.as_dict()["gathers"]   # growth rows + watermark
    gathers.project_gather_bytes(64)      # pod-scale flat projection
    advice = gathers.GatherAdvisor().advise()
    advice["candidates"][0]               # biggest projected consumer
    obs.export(gathers.gather_report(), fmt="jsonl")

A cheap, device-free example (the doctest tier-1 actually runs) — two steps
of BENCH_r05's mAP workload at 85,760 cat bytes/step project to exactly the
archived 5,402,880 bytes/chip/step at 64 chips, and the advisor names the
sketch route first::

    >>> from torchmetrics_tpu.observability.gathers import (
    ...     GatherAdvisor, project_gather_bytes)
    >>> rows = {"MeanAveragePrecision#0": {
    ...     "class": "MeanAveragePrecision",
    ...     "gathers": {"steps": 2, "cat_elements": 13440,
    ...                 "cat_bytes": 171520, "ew_bytes_per_step": 85760.0,
    ...                 "hwm_bytes": 171520, "leaves": {}}}}
    >>> proj = project_gather_bytes(64, report={"metrics": rows})
    >>> proj["metrics"]["MeanAveragePrecision#0"]["projected_bytes_per_chip_per_step"]
    5402880
    >>> advice = GatherAdvisor(n_chips=64).advise(report={"metrics": rows})
    >>> advice["candidates"][0]["recommendation"]
    'sketch-first'
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

import jax

from torchmetrics_tpu.observability import registry
from torchmetrics_tpu.utilities.benchmark import (
    RING_GRANULE_BYTES,
    _is_psum_shaped,
    tiled_allgather_bytes,
    two_stage_gather_bytes,
)

__all__ = [
    "APPROX_COMMITS",
    "GATHER_DECISION_KIND",
    "GATHER_LEDGER_KIND",
    "GATHER_REPORT_KIND",
    "GatherAdvisor",
    "SKETCH_ALTERNATIVES",
    "cat_growth_rows",
    "disable_gather_telemetry",
    "enable_gather_telemetry",
    "gather_report",
    "gather_telemetry_enabled",
    "project_gather_bytes",
    "sketch_alternative_for",
]

_log = logging.getLogger("torchmetrics_tpu.observability")

#: ``kind`` stamp on every advisor ledger entry (JSONL consumers filter on it
#: exactly like ``sharding_decision`` / ``autotune_decision``)
GATHER_LEDGER_KIND = "gather_advice"
#: ``kind`` stamp on every actuation state-machine transition the advisor
#: ledgers (propose/arm/commit/veto/rollback/audit — the gather plane's
#: counterpart of ``sharding_decision``)
GATHER_DECISION_KIND = "gather_decision"
#: ``kind`` stamp on the front-door report payload
GATHER_REPORT_KIND = "gather_report"

#: The sketch layer's shipped fixed-shape alternatives, by base metric name
#: (Binary/Multiclass/Multilabel prefixes are stripped by
#: :func:`sketch_alternative_for`).  Each alternative replaces an unbounded
#: cat state with a fixed-shape state that rides the psum family — per-step
#: gather bytes drop to zero.
SKETCH_ALTERNATIVES: Dict[str, str] = {
    "AUROC": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "AveragePrecision": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "PrecisionRecallCurve": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "ROC": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "MeanAveragePrecision": (
        'approx="sketch" score-histogram mode: fixed-shape per-(class, '
        "IoU-bucket) histograms ride the psum family, bounded-error attested"
    ),
    "ROUGEScore": (
        'approx="reservoir" bottom-k-by-hash corpus sample: ONE fixed-shape '
        "gather regardless of corpus size, unsampled-mass bound attested"
    ),
    "BLEUScore": (
        'approx="reservoir" bottom-k-by-hash corpus sample: fixed-shape '
        "sentence-stat reservoir, unsampled-mass bound attested"
    ),
    "SacreBLEUScore": (
        'approx="reservoir" bottom-k-by-hash corpus sample: fixed-shape '
        "sentence-stat reservoir, unsampled-mass bound attested"
    ),
}

#: The runtime switch :meth:`GatherAdvisor.commit` applies per metric class:
#: ``Metric.set_approx(mode)`` converts the cat states to the sketch-backed
#: fixed-shape family (one expected new-key compile miss per metric).
APPROX_COMMITS: Dict[str, str] = {
    "MeanAveragePrecision": "sketch",
    "ROUGEScore": "reservoir",
    "BLEUScore": "reservoir",
    "SacreBLEUScore": "reservoir",
}


def sketch_alternative_for(cls_name: str) -> Optional[str]:
    """The sketch layer's shipped fixed-shape alternative for metric class
    ``cls_name``, or ``None`` when none exists (the ``approx="sketch"`` /
    ``approx="reservoir"`` modes cover mAP and the corpus text metrics)."""
    base = cls_name
    for prefix in ("Binary", "Multiclass", "Multilabel"):
        if base.startswith(prefix):
            base = base[len(prefix) :]
            break
    return SKETCH_ALTERNATIVES.get(base)


# ---------------------------------------------------------------------------
# layer 1: live cat-state growth sizing
# ---------------------------------------------------------------------------


def _leaf_sizes(leaf: Any) -> Tuple[int, int]:
    """``(elements, bytes)`` of one state leaf's unpadded items — the same
    per-item ``size * itemsize`` accounting ``split_state_bytes`` uses, so
    live growth rows reconcile exactly with the bench's analytic tables."""
    elements = nbytes = 0
    for v in jax.tree.leaves(leaf):
        size = int(getattr(v, "size", 1))
        dtype = getattr(v, "dtype", None)
        itemsize = int(getattr(dtype, "itemsize", 8))
        elements += size
        nbytes += size * itemsize
    return elements, nbytes


def cat_growth_rows(
    metric: Any,
    partial_states: Iterable[Mapping[str, Any]],
    accumulated_states: Optional[Iterable[Mapping[str, Any]]] = None,
) -> Dict[str, Dict[str, int]]:
    """Size one update step's gather-family growth for ``metric``.

    ``partial_states`` holds this step's freshly-updated per-device states;
    ``accumulated_states`` (when given) the running per-device states after
    the merge.  For every leaf in ``metric._reductions`` that syncs by
    gather (cat/None/callable/structural — everything
    ``_is_psum_shaped`` excludes), returns the *unpadded* appended
    ``{"elements", "bytes"}`` summed over all devices' partials — matching
    the whole-update ``cat_state_bytes_per_step`` accounting bench.py's
    ``state_reduce_bytes_table`` archives — plus ``total_bytes`` (the
    running cat size, for the high-watermark) from the accumulated states.

    Pure host-side sizing: reads shapes/dtypes only, never device buffers,
    so feeding the registry from an update loop cannot retrace anything.
    """
    reductions = getattr(metric, "_reductions", None) or {}
    partials = list(partial_states)
    accumulated = list(accumulated_states) if accumulated_states is not None else None
    rows: Dict[str, Dict[str, int]] = {}
    for name, reduce in sorted(reductions.items()):
        if _is_psum_shaped(reduce):
            continue
        elements = nbytes = 0
        for st in partials:
            if name not in st:
                continue
            e, b = _leaf_sizes(st[name])
            elements += e
            nbytes += b
        row = {"elements": elements, "bytes": nbytes}
        if accumulated is not None:
            total = 0
            for st in accumulated:
                if name in st:
                    total += _leaf_sizes(st[name])[1]
            row["total_bytes"] = total
        rows[name] = row
    return rows


# ---------------------------------------------------------------------------
# arming (the second half of the double gate)
# ---------------------------------------------------------------------------


def enable_gather_telemetry() -> None:
    """Arm the gather plane: live cat-state growth accounting in
    ``DeferredRaggedSync.update`` plus block-until-ready measured timing of
    the deferred ragged gather.

    Nothing records until ``observability.enable()`` is also on.  Arming
    changes no cache key and adds no retrace: growth sizing reads host-side
    shapes the update already computed, and the measured timing waits on a
    collective that already runs (the wait is observation cost at the host
    boundary, not graph change)."""
    registry.set_gather_armed(True)


def disable_gather_telemetry() -> None:
    """Disarm the gather plane.  Recorded growth rows and measured buckets
    are kept (clear them with ``reset_telemetry()``); new steps stop being
    sized and the gather stops being block-until-ready timed."""
    registry.set_gather_armed(False)


def gather_telemetry_enabled() -> bool:
    """True while the gather plane is armed (the registry gate)."""
    return registry.gather_armed()


# ---------------------------------------------------------------------------
# layer 2: pod-scale projection
# ---------------------------------------------------------------------------


def _gather_rows(report: Optional[Mapping[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """``{label: {"class", "gathers"}}`` for every metric row carrying live
    cat-growth attribution, from ``report`` (default: the live registry)."""
    rep = report if report is not None else registry.report()
    out: Dict[str, Dict[str, Any]] = {}
    for label, row in rep.get("metrics", {}).items():
        g = row.get("gathers")
        if isinstance(g, Mapping) and int(g.get("steps", 0)) > 0:
            out[label] = {"class": row.get("class", label), "gathers": g}
    return out


def project_gather_bytes(
    n_chips: int, report: Optional[Mapping[str, Any]] = None
) -> Dict[str, Any]:
    """Extrapolate live cat-state attribution to an ``n_chips`` mesh with
    the flat all-gather model: each chip receives every other chip's
    per-step cat shard, so per-chip traffic is
    ``(n_chips - 1) x mean bytes/step``.

    ``report`` defaults to the live registry report; pass an archived one to
    project old runs.  Under BENCH_r05's mAP workload (85,760 cat
    bytes/step) this reproduces the archive's 5,402,880 bytes/chip/step at
    64 chips exactly — the exact-figure contract ``test_gathers.py`` and the
    bench's gather leg both assert.

    Returns per-metric rows (mean ``bytes_per_step``, the EW growth rate,
    per-leaf projections) plus ``total_bytes_per_chip_per_step``.
    """
    n = int(n_chips)
    metrics: Dict[str, Dict[str, Any]] = {}
    total = 0
    for label, row in sorted(_gather_rows(report).items()):
        g = row["gathers"]
        steps = max(int(g["steps"]), 1)
        bps = int(round(int(g["cat_bytes"]) / steps))
        projected = max(n - 1, 0) * bps
        leaves = {}
        for name, leaf in sorted(dict(g.get("leaves", {})).items()):
            lsteps = max(int(leaf.get("steps", steps)), 1)
            lbps = int(round(int(leaf.get("bytes", 0)) / lsteps))
            leaves[name] = {
                "bytes_per_step": lbps,
                "projected_bytes_per_chip_per_step": max(n - 1, 0) * lbps,
            }
        metrics[label] = {
            "class": row["class"],
            "steps": int(g["steps"]),
            "bytes_per_step": bps,
            "ew_bytes_per_step": float(g.get("ew_bytes_per_step", 0.0)),
            "hwm_bytes": int(g.get("hwm_bytes", 0)),
            "projected_bytes_per_chip_per_step": projected,
            "leaves": leaves,
        }
        total += projected
    return {
        "n_chips": n,
        "model": "flat",
        "metrics": metrics,
        "total_bytes_per_chip_per_step": total,
    }


# ---------------------------------------------------------------------------
# layer 3: report-only advice
# ---------------------------------------------------------------------------


class GatherAdvisor:
    """Report-only advisor ranking cat-state consumers by projected
    pod-scale gather bytes.

    For each metric with live cat-growth attribution, :meth:`advise`
    projects the flat all-gather cost at ``n_chips`` (linear in chip count —
    the MLPerf pod paper's scaling cap, arxiv 1909.09756) and models both
    escape hatches:

    * ``two_stage`` — gather over ICI inside each host, exchange one
      aggregated copy per host over DCN
      (``utilities.benchmark.two_stage_gather_bytes``): cross-host bytes
      scale with hosts, not chips, an ``~n_local_devices x`` DCN cut;
    * ``sketch`` — replace the cat leaf with a fixed-shape sketch state that
      rides the psum family: per-step gather bytes drop to zero.  Where the
      sketch layer already ships the alternative (AUROC / AveragePrecision /
      ROC / PrecisionRecallCurve ``thresholds=N`` binned modes) the advisor
      quotes it by name; for mAP/ROUGE the recommendation points at ROADMAP
      open item 5's sketch-backed variants.

    Candidates at or above ``sketch_first_bytes`` projected flat bytes are
    recommended ``"sketch-first"`` (the two-stage route still moves every
    byte once — only a sketch caps the linear-in-steps growth); smaller
    consumers get ``"two-stage"``.  Every :meth:`advise` lands in
    :meth:`decision_ledger` as a ``kind: "gather_advice"`` row and mirrors
    into the flight recorder's ``gather`` category when armed.

    :meth:`advise` never touches metric config.  :meth:`recommend` wraps it
    in the established actuation state machine (``observe → candidate →
    trial → committed``, mirroring :class:`~torchmetrics_tpu.observability.memory.ShardingAdvisor`):
    a commit applies each sketch-first candidate's shipped runtime switch
    (``Metric.set_approx`` per :data:`APPROX_COMMITS` — one expected
    ``new-key`` compile miss per converted metric, audited by
    :meth:`retrace_report`) and flips two-stage candidates' shared
    :class:`~torchmetrics_tpu.parallel.ragged.DeferredRaggedSync` onto the
    ICI→DCN route (no new compile key — the crossing is host-side).  Health
    alerts wired through :meth:`guardrail_sink` (including the accuracy
    plane's shadow-exact audit breaches) veto a pending trial or roll back
    a commit; every transition lands in the ledger as a
    ``kind: "gather_decision"`` row.
    """

    def __init__(
        self,
        n_chips: int = 64,
        n_local_devices: int = 8,
        granule: int = RING_GRANULE_BYTES,
        sketch_first_bytes: int = 1 << 20,
        veto_severity: str = "warning",
    ) -> None:
        self.n_chips = int(n_chips)
        #: chips per host in the projected mesh (v4-8 host granularity);
        #: hosts = ceil(n_chips / n_local_devices)
        self.n_local_devices = max(int(n_local_devices), 1)
        self.granule = int(granule)
        #: projected flat bytes/chip/step at/above this make the candidate
        #: sketch-first: two-stage still ships every byte once per step,
        #: only a fixed-shape sketch kills the linear-in-steps growth
        self.sketch_first_bytes = int(sketch_first_bytes)
        #: health alerts at/above this severity veto a pending trial or roll
        #: back a committed conversion (see :meth:`guardrail_sink`)
        self.veto_severity = veto_severity
        self.state = "observe"
        self._seq = 0
        self._ledger: List[Dict[str, Any]] = []
        #: staged proposal: {"targets": [(label, obj, action, arg, pre_bps)]}
        self._candidate: Optional[Dict[str, Any]] = None
        #: rollback tokens for the committed targets
        self._previous: Optional[List[Tuple[str, Any, str, Any]]] = None
        #: the shared accumulator the last commit converted against, if any
        self._commit_accumulator: Optional[Any] = None
        self._commit_cache_baseline: Optional[Dict[str, Any]] = None
        self._expected_retraces: Dict[str, Any] = {"new_keys": 0, "causes": []}
        #: measured post-commit byte cuts, by metric label (advice lines
        #: quoting a shipped alternative carry these once measured)
        self._committed_cuts: Dict[str, Dict[str, Any]] = {}
        self.counts: Dict[str, int] = {
            "proposals": 0,
            "trials": 0,
            "commits": 0,
            "vetoes": 0,
            "rollbacks": 0,
        }

    def advise(
        self,
        report: Optional[Mapping[str, Any]] = None,
        n_chips: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rank every live cat-state consumer by projected pod-scale bytes.

        ``report`` defaults to the live registry report (pass an archived
        one to re-advise old runs); ``n_chips`` defaults to the advisor's.
        """
        n = int(n_chips or self.n_chips)
        n_local = min(self.n_local_devices, n)
        n_hosts = max(1, -(-n // n_local))
        rows = _gather_rows(report)
        commits = self._measure_commits(rows)
        candidates: List[Dict[str, Any]] = []
        total_flat = total_two_stage = 0
        for label, row in sorted(rows.items()):
            g = row["gathers"]
            steps = max(int(g["steps"]), 1)
            bps = int(round(int(g["cat_bytes"]) / steps))
            if bps <= 0:
                continue
            flat = max(n - 1, 0) * bps
            tiled = int(tiled_allgather_bytes(bps, n, self.granule))
            stages = two_stage_gather_bytes(bps, n_hosts, n_local, self.granule)
            alternative = sketch_alternative_for(str(row["class"]))
            recommendation = (
                "sketch-first" if flat >= self.sketch_first_bytes else "two-stage"
            )
            candidates.append(
                {
                    "metric": label,
                    "class": row["class"],
                    "steps": int(g["steps"]),
                    "bytes_per_step": bps,
                    "ew_bytes_per_step": float(g.get("ew_bytes_per_step", 0.0)),
                    "hwm_bytes": int(g.get("hwm_bytes", 0)),
                    "projected_flat_bytes_per_chip_per_step": flat,
                    "projected_tiled_bytes_per_chip_per_step": tiled,
                    "two_stage_dcn_bytes_per_chip_per_step": stages["two_stage"],
                    "two_stage_ici_bytes_per_chip_per_step": stages["ici"],
                    "two_stage_cut_bytes_per_chip_per_step": stages["flat"]
                    - stages["two_stage"],
                    # a sketch state is fixed-shape psum: the whole projected
                    # gather cost goes away, bounded-error attested
                    "sketch_cut_bytes_per_chip_per_step": flat,
                    "sketch_alternative": alternative,
                    "recommendation": recommendation,
                }
            )
            total_flat += flat
            total_two_stage += stages["two_stage"]
        candidates.sort(
            key=lambda c: (-c["projected_flat_bytes_per_chip_per_step"], c["metric"])
        )
        # advice lines: one per live candidate, plus one per committed
        # conversion quoting a shipped alternative — the committed lines
        # carry the measured post-commit byte cut once post-commit steps
        # have been observed
        recommended = [f"{c['metric']}: {c['recommendation']}" for c in candidates]
        for label, cut in sorted(commits.items()):
            if not cut.get("alternative"):
                continue
            if cut.get("measured"):
                recommended.append(
                    f"{label}: {cut['action']} committed — measured cut "
                    f"{int(cut['cut_bytes_per_step'])} B/step"
                )
            else:
                recommended.append(
                    f"{label}: {cut['action']} committed — cut pending "
                    "post-commit steps"
                )
        advice = {
            "kind": GATHER_LEDGER_KIND,
            "seq": self._seq,
            "n_chips": n,
            "n_hosts": n_hosts,
            "n_local_devices": n_local,
            "granule_bytes": self.granule,
            "sketch_first_bytes": self.sketch_first_bytes,
            "total_projected_flat_bytes_per_chip_per_step": total_flat,
            "total_two_stage_dcn_bytes_per_chip_per_step": total_two_stage,
            "candidates": candidates,
            "commits": commits,
            "recommended": recommended,
            "note": (
                "actuation via recommend(apply=True): sketch-first candidates "
                "convert through Metric.set_approx, two-stage candidates flip "
                "the shared DeferredRaggedSync route; candidates ranked by "
                "projected flat bytes/chip/step"
            ),
        }
        self._seq += 1
        self._ledger.append(advice)
        if candidates:
            top = candidates[0]
            registry.gather_trace(
                top["metric"],
                "advice",
                {
                    "seq": advice["seq"],
                    "n_chips": n,
                    "recommendation": top["recommendation"],
                    "projected_flat_bytes_per_chip_per_step": top[
                        "projected_flat_bytes_per_chip_per_step"
                    ],
                    "candidates": len(candidates),
                },
            )
        import copy

        return copy.deepcopy(advice)

    def _measure_commits(
        self, rows: Mapping[str, Mapping[str, Any]]
    ) -> Dict[str, Dict[str, Any]]:
        """Refresh each committed conversion's measured post-commit byte cut
        from the current growth rows: cut = pre-commit bytes/step minus the
        bytes/step observed *since* the commit (0 new gather bytes for a
        sketch conversion — its states ride the psum family).  Returns a
        deep-copyable ``{label: cut}`` block for the advice payload."""
        for label, cut in self._committed_cuts.items():
            row = rows.get(label)
            steps_now = bytes_now = 0
            if row is not None:
                g = row["gathers"]
                steps_now = int(g.get("steps", 0))
                bytes_now = int(g.get("cat_bytes", 0))
            d_steps = steps_now - int(cut["steps_at_commit"])
            if d_steps > 0:
                post = int(round((bytes_now - int(cut["bytes_at_commit"])) / d_steps))
                cut["post_bytes_per_step"] = post
                cut["cut_bytes_per_step"] = int(cut["pre_bytes_per_step"]) - post
                cut["measured"] = True
        import copy

        return copy.deepcopy(self._committed_cuts)

    # --------------------------------------------------------- actuation loop
    def recommend(
        self,
        metrics: Iterable[Any],
        n_chips: Optional[int] = None,
        apply: bool = False,
        targets: Optional[Iterable[str]] = None,
        report: Optional[Mapping[str, Any]] = None,
        accumulator: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """:meth:`advise` promoted to a proposal: rank the candidates, stage
        each one's shipped escape hatch, and (with ``apply=True``) arm and
        commit them onto the live metrics.

        ``metrics`` holds metric instances or ``(label, metric)`` pairs
        (unlabelled metrics take their telemetry label); only candidates
        matching a provided metric are staged.  ``targets`` restricts the
        staged set to the named labels.  Sketch-first candidates whose class
        ships a runtime switch (:data:`APPROX_COMMITS`) stage a
        ``set_approx`` conversion; two-stage candidates stage a route flip
        on ``accumulator`` (the shared
        :class:`~torchmetrics_tpu.parallel.ragged.DeferredRaggedSync`) when
        one is given.  Returns the advice payload extended with an
        ``actuation`` block.  Without ``apply`` the machine stops in
        ``candidate``: call :meth:`arm` then :meth:`commit` by hand, exactly
        like the sharding advisor's staged flow.
        """
        pairs: List[Tuple[str, Any]] = []
        for item in metrics:
            if isinstance(item, tuple) and len(item) == 2 and isinstance(item[0], str):
                pairs.append(item)
            else:
                t = registry.telemetry_for(item, create=False)
                pairs.append((t.label if t is not None else type(item).__name__, item))
        advice = self.advise(report=report, n_chips=n_chips)
        by_label = dict(pairs)
        wanted = set(targets) if targets is not None else None
        staged: List[Tuple[str, Any, str, Any, int]] = []
        route_staged = False
        for c in advice["candidates"]:
            label = c["metric"]
            if wanted is not None and label not in wanted:
                continue
            metric = by_label.get(label)
            if metric is None:
                continue
            mode = APPROX_COMMITS.get(str(c["class"]))
            if c["recommendation"] == "sketch-first" and mode is not None:
                staged.append((label, metric, "approx", mode, c["bytes_per_step"]))
            elif c["recommendation"] == "two-stage" and accumulator is not None:
                # the route is a property of the shared accumulator, not of
                # any one metric: flip it once, attributed to the biggest
                # two-stage consumer
                if not route_staged:
                    staged.append(
                        (label, accumulator, "route", "two_stage", c["bytes_per_step"])
                    )
                    route_staged = True
        prior = self.state
        self._candidate = {"advice": advice, "targets": staged, "accumulator": accumulator}
        self.state = "candidate"
        self.counts["proposals"] += 1
        self._record(
            "propose",
            state_from=prior,
            targets=[f"{label}:{action}={arg}" for label, _, action, arg, _ in staged],
            trigger={
                "n_chips": advice["n_chips"],
                "total_projected_flat_bytes_per_chip_per_step": advice[
                    "total_projected_flat_bytes_per_chip_per_step"
                ],
            },
            rationale=(
                f"staged {len(staged)} gather escape hatch(es): sketch-first "
                "converts via set_approx, two-stage flips the deferred-gather route"
            ),
        )
        out = dict(advice)
        out["actuation"] = {
            "state": self.state,
            "targets": [f"{label}:{action}={arg}" for label, _, action, arg, _ in staged],
            "applied": False,
        }
        if apply:
            self.arm()
            entry = self.commit()
            out["actuation"] = {
                "state": self.state,
                "targets": entry["targets"],
                "applied": bool(entry["applied"]),
                "skipped": entry["trigger"].get("skipped", []),
                "expected_retraces": entry.get("expected_retraces"),
            }
        return out

    def arm(self) -> Dict[str, Any]:
        """Stage the proposed conversions for commit: enter ``trial``, during
        which any guardrail alert vetoes the pending actuation."""
        if self.state != "candidate" or self._candidate is None:
            raise RuntimeError(
                f"GatherAdvisor.arm: no candidate to stage (state {self.state!r}); "
                "call recommend() first"
            )
        self.state = "trial"
        self.counts["trials"] += 1
        return self._record(
            "arm",
            state_from="candidate",
            targets=[
                f"{label}:{action}={arg}"
                for label, _, action, arg, _ in self._candidate["targets"]
            ],
            rationale="candidate conversions staged; guardrails may veto until commit()",
        )

    def commit(self) -> Dict[str, Any]:
        """Apply the staged conversions to the live objects.

        ``approx`` targets go through ``Metric.set_approx`` — a metric that
        refuses (no runtime-switch hook, invalid mode for its config) is
        skipped and recorded, never silently forced; ``route`` targets flip
        the shared accumulator's gather route.  The compile-cache baseline
        is captured first so :meth:`retrace_report` can prove the transition
        cost exactly its expected one ``new-key`` miss per converted metric
        (route flips are host-side and expect none) and nothing more — 0
        steady-state retraces."""
        if self.state != "trial" or self._candidate is None:
            raise RuntimeError(
                f"GatherAdvisor.commit: no staged trial (state {self.state!r}) — "
                "it may have been vetoed by a guardrail; check decision_ledger()"
            )
        from torchmetrics_tpu.core.compile import cache_stats

        self._commit_cache_baseline = cache_stats()
        rows = _gather_rows(None)
        previous: List[Tuple[str, Any, str, Any]] = []
        applied: List[str] = []
        skipped: List[Dict[str, str]] = []
        converted: set = set()
        accumulator = self._candidate.get("accumulator")
        for label, obj, action, arg, pre_bps in self._candidate["targets"]:
            try:
                if action == "approx":
                    old = (obj.approx, obj.approx_error)
                    obj.set_approx(arg)
                    converted.add(id(obj))
                    if accumulator is not None:
                        # the old-layout exact partials cannot merge with
                        # post-conversion updates; drop them at the boundary
                        for key, member in accumulator._members.items():
                            if member is obj:
                                accumulator.reset_for(key)
                else:
                    old = obj.set_route(arg)
            except (ValueError, KeyError) as err:
                skipped.append({"target": f"{label}:{action}={arg}", "error": str(err)})
                continue
            previous.append((label, obj, action, old))
            applied.append(f"{label}:{action}={arg}")
            if action == "approx":
                alternative = sketch_alternative_for(type(obj).__name__)
            else:
                alternative = (
                    "two-stage route: in-host ICI all-gather then one per-host "
                    "DCN exchange (cross-host bytes scale with hosts, not chips)"
                )
            g = rows.get(label, {}).get("gathers", {})
            self._committed_cuts[label] = {
                "action": f"{action}={arg}",
                "alternative": alternative,
                "pre_bytes_per_step": int(pre_bps),
                "steps_at_commit": int(g.get("steps", 0)),
                "bytes_at_commit": int(g.get("cat_bytes", 0)),
                "post_bytes_per_step": None,
                "cut_bytes_per_step": None,
                "measured": False,
            }
        expected = {
            # set_approx re-registers leaves and bumps the config
            # fingerprint: exactly one new-key/invalidation miss per
            # converted metric; route flips change no compile key
            "new_keys": len(converted),
            "causes": ["invalidation", "new-key"] if converted else [],
            "entrypoint": None,
        }
        self._previous = previous
        self._commit_accumulator = accumulator
        self._expected_retraces = expected
        self.state = "committed"
        self.counts["commits"] += 1
        entry = self._record(
            "commit",
            state_from="trial",
            targets=applied,
            applied=bool(applied),
            trigger={"applied": applied, "skipped": skipped},
            expected_retraces=expected,
            rationale=(
                f"applied {len(applied)} gather escape hatch(es); each approx "
                "conversion re-fingerprints its metric for exactly one new-key "
                "compile per entrypoint"
                if applied
                else "no target accepted a conversion; nothing applied"
            ),
        )
        self._candidate = None
        return entry

    def veto(self, reason: str = "manual", alert: Optional[Any] = None) -> Dict[str, Any]:
        """Veto the pending trial (guardrails call this through
        :meth:`guardrail_sink`; callers may veto manually)."""
        if self.state != "trial":
            raise RuntimeError(
                f"GatherAdvisor.veto: no pending trial to veto (state {self.state!r})"
            )
        return self._veto(reason, alert=alert)

    def rollback(
        self,
        reason: str = "manual",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Restore every committed target's previous config (``set_approx``
        back to the exact cat states, the accumulator back to its previous
        route) and ledger why.  A shadow-exact audit breach arriving through
        :meth:`guardrail_sink` lands here — sketch commits whose error
        attestation fails roll back to exact."""
        if self.state != "committed" or self._previous is None:
            raise RuntimeError(
                f"GatherAdvisor.rollback: nothing committed to roll back "
                f"(state {self.state!r})"
            )
        restored = []
        accumulator = getattr(self, "_commit_accumulator", None)
        for label, obj, action, old in self._previous:
            if action == "approx":
                obj.set_approx(old[0], old[1])
                if accumulator is not None:
                    # post-conversion sketch partials cannot merge with the
                    # restored exact layout either — same boundary, reversed
                    for key, member in accumulator._members.items():
                        if member is obj:
                            accumulator.reset_for(key)
            else:
                obj.set_route(old)
            self._committed_cuts.pop(label, None)
            restored.append(f"{label}:{action}")
        self.counts["rollbacks"] += 1
        entry = self._record(
            "rollback",
            state_from="committed",
            state_to="observe",
            targets=restored,
            applied=True,
            alert=alert,
            error=error,
            rationale=f"rolled back committed gather conversion(s): {reason}",
        )
        self.state = "observe"
        self._previous = None
        return entry

    def guardrail_sink(self, min_severity: Optional[str] = None) -> Any:
        """An ``AlertSink`` wiring :class:`~torchmetrics_tpu.observability.health.HealthMonitor`
        alerts into the loop: alerts at/above ``min_severity`` (default the
        advisor's ``veto_severity``) veto a pending trial or roll back a
        committed conversion, in-band — the same guardrail contract as the
        sharding advisor's.  Shadow-exact audit breaches surfaced as health
        alerts flow through the same sink."""
        from torchmetrics_tpu.observability.health import CallbackAlertSink, _severity_rank

        severity = self.veto_severity if min_severity is None else min_severity
        _severity_rank(severity)  # validates
        return CallbackAlertSink(self._on_alert, min_severity=severity)

    def _on_alert(self, alert: Any) -> None:
        if self.state == "trial":
            self._veto("health_alert", alert=alert)
        elif self.state == "committed" and self._previous is not None:
            self.rollback(reason="health_alert", alert=alert)

    def _veto(
        self, reason: str, alert: Optional[Any] = None, error: Optional[str] = None
    ) -> Dict[str, Any]:
        staged = self._candidate["targets"] if self._candidate else []
        self.counts["vetoes"] += 1
        entry = self._record(
            "veto",
            state_from=self.state,
            state_to="observe",
            targets=[f"{label}:{action}={arg}" for label, _, action, arg, _ in staged],
            applied=False,
            alert=alert,
            error=error,
            rationale=f"pending gather conversion vetoed: {reason}",
        )
        self.state = "observe"
        self._candidate = None
        return entry

    def retrace_report(self) -> Dict[str, Any]:
        """Compile-cache delta since the last commit, judged against the
        ledgered expectation — the proof that a gather conversion costs
        exactly one ``new-key`` miss per converted metric and that steady
        state re-traces **zero** times.  Ledgered as an ``audit`` decision."""
        from torchmetrics_tpu.core.compile import cache_stats_since

        if self._commit_cache_baseline is None:
            raise RuntimeError("GatherAdvisor.retrace_report: no commit to audit")
        delta = cache_stats_since(self._commit_cache_baseline)
        delta_causes = delta["miss_causes"]
        extra_misses = int(delta["misses"])
        expected = self._expected_retraces
        ok = (
            extra_misses <= expected["new_keys"]
            and sum(delta_causes.values()) <= expected["new_keys"]
            and all(cause in expected["causes"] for cause in delta_causes)
        )
        audit = {
            "extra_traces": int(delta["traces"]),
            "extra_misses": extra_misses,
            "miss_causes": delta_causes,
            "expected": dict(expected),
            "ok": bool(ok),
        }
        self._record(
            "audit",
            state_from=self.state,
            state_to=self.state,
            trigger=audit,
            rationale=(
                "trace-safety audit: cache delta since commit matches the "
                "ledgered expectation"
                if ok
                else "trace-safety audit FAILED: unexpected compile-cache "
                "traffic since gather conversion commit"
            ),
        )
        return audit

    def _record(
        self,
        action: str,
        state_from: str,
        state_to: Optional[str] = None,
        targets: Optional[List[str]] = None,
        applied: Optional[bool] = None,
        trigger: Optional[Mapping[str, Any]] = None,
        rationale: str = "",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
        expected_retraces: Optional[Mapping[str, Any]] = None,
    ) -> Dict[str, Any]:
        import copy

        entry: Dict[str, Any] = {
            "kind": GATHER_DECISION_KIND,
            "seq": self._seq,
            "action": action,
            "state_from": state_from,
            "state_to": self.state if state_to is None else state_to,
            "targets": list(targets or []),
            "applied": bool(applied) if applied is not None else None,
            "trigger": dict(trigger) if trigger else {},
            "rationale": rationale,
        }
        if alert is not None:
            entry["alert"] = alert.as_dict() if hasattr(alert, "as_dict") else dict(alert)
        if error is not None:
            entry["error"] = error
        if expected_retraces is not None:
            entry["expected_retraces"] = dict(expected_retraces)
        self._seq += 1
        self._ledger.append(entry)
        registry.gather_trace(
            "_advisor",
            f"decision/{action}",
            {"seq": entry["seq"], "state_to": entry["state_to"], "targets": entry["targets"]},
        )
        return copy.deepcopy(entry)

    def report(self) -> Dict[str, Any]:
        """The actuation block for the export front door."""
        return {
            "state": self.state,
            "counts": dict(self.counts),
            "decisions": len(self._ledger),
            "expected_retraces": dict(self._expected_retraces),
        }

    def decision_ledger(self) -> List[Dict[str, Any]]:
        """Every entry this advisor produced, oldest first — advice payloads
        (``kind == "gather_advice"``) interleaved with actuation transitions
        (``kind == "gather_decision"``) in one seq-ordered stream, safe to
        mutate."""
        import copy

        return copy.deepcopy(self._ledger)

    def export_ledger(
        self, path: Optional[str] = None, stream: Optional[Any] = None
    ) -> List[str]:
        """Write the ledger through the export front door: one JSONL line
        per advice, stamped with ``schema_version`` + process identity and
        parseable back via ``observability.parse_export_line`` — the same
        contract as ``ShardingAdvisor.export_ledger``."""
        from torchmetrics_tpu.observability.export import JSONLinesExporter

        exporter = JSONLinesExporter(path=path, stream=stream)
        return [exporter.export(entry) for entry in self._ledger]


# ---------------------------------------------------------------------------
# the front-door report
# ---------------------------------------------------------------------------


def gather_report(
    n_chips: Iterable[int] = (8, 16, 64),
    advise_at: Optional[int] = 64,
    report: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    """One ``kind: "gather_report"`` payload tying all three layers
    together, ready for ``observability.export`` (the JSONL line parses back
    through ``parse_export_line``; the Prometheus exporter renders the
    ``tm_tpu_gather_*`` families from it).

    Layout::

        {"schema": 1, "kind": "gather_report", "armed": bool,
         "gather": {
            "metrics": {label: gathers-block, ...},   # live growth rows
            "projection": {"8": ..., "16": ..., "64": ...},
            "advice": {...}}}                         # iff advise_at

    ``n_chips`` picks the projected mesh sizes; ``advise_at`` the mesh the
    advisor ranks against (``None`` skips advice).
    """
    rep = report if report is not None else registry.report()
    payload: Dict[str, Any] = {
        "schema": 1,
        "kind": GATHER_REPORT_KIND,
        "armed": gather_telemetry_enabled(),
        "enabled": registry.enabled(),
        "gather": {
            "metrics": {
                label: dict(row["gathers"])
                for label, row in sorted(_gather_rows(rep).items())
            },
            "projection": {
                str(int(n)): project_gather_bytes(int(n), report=rep)
                for n in n_chips
            },
        },
    }
    if advise_at is not None:
        payload["gather"]["advice"] = GatherAdvisor(n_chips=int(advise_at)).advise(
            report=rep
        )
    return payload


# honour TM_TPU_GATHER_TELEMETRY=1 the way registry honours TM_TPU_TELEMETRY
if os.environ.get("TM_TPU_GATHER_TELEMETRY", "").strip().lower() in (
    "1",
    "true",
    "on",
    "yes",
):  # pragma: no cover - env-driven path
    enable_gather_telemetry()
