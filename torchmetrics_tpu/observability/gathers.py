"""Gather-plane observability: live cat-state attribution, pod-scale
projection, and a report-only :class:`GatherAdvisor`.

The psum family is fully instrumented (per-bucket measured timing, ring and
two-stage byte models, residuals, ShardingAdvisor); this module does the same
for the *gather family* — the cat/reservoir/structural leaves that
``core.reductions.sync_leaf`` lowers to padded all-gathers and that grow
per step instead of combining.  Three layers:

1. **Live cat-state growth accounting** — every
   :meth:`~torchmetrics_tpu.parallel.ragged.DeferredRaggedSync.update_for`
   step sizes the gather-family leaves it appended (:func:`cat_growth_rows`,
   unpadded item bytes summed over the local mesh — the same whole-update
   accounting ``bench.py``'s ``cat_state_bytes_per_step`` uses) and folds
   them into the telemetry registry: per-leaf elements/bytes per step, an
   exponentially-weighted growth rate, and the cat-state high-watermark.
   The deferred gather itself is timed block-until-ready at the host
   boundary and lands in per-bucket ``measured_us`` rows
   (``registry.record_measured_gather``) exactly the way coalesced psum
   buckets already do, with the flat ``(n-1)*B`` and granule-tiled
   (``utilities.benchmark.tiled_allgather_bytes``) byte models alongside so
   exporters can show the model-vs-measured residual.
2. **Pod-scale projection** — :func:`project_gather_bytes` extrapolates the
   live per-step attribution to 8/16/64-chip meshes with the flat all-gather
   model.  This is how the bench reproduces BENCH_r05's mAP figure of
   5,402,880 bytes/chip/step at 64 chips from *live* data (the gather
   family's counterpart of the ShardingAdvisor's 33,570,840 psum-byte
   reproduction).
3. **Report-only advice** — :class:`GatherAdvisor` ranks cat-state consumers
   by projected pod-scale bytes and models both escape hatches: the
   two-stage ICI-gather→DCN-exchange route (cross-host bytes scale with
   hosts, not chips — ``utilities.benchmark.two_stage_gather_bytes``, after
   arxiv 2204.06514) and the sketch-mode cut (a fixed-shape state rides the
   psum family instead; where the sketch layer already ships one — e.g.
   AUROC's ``thresholds=N`` binned mode — the advisor quotes it by name).
   Every ``advise()`` lands in a ledger as a ``kind: "gather_advice"`` row,
   exportable through the JSONL front door.

Everything is double-gated: :func:`enable_gather_telemetry` arms the plane,
but nothing records until ``observability.enable()`` is also on (mirroring
the memory and accuracy planes).  Arming adds **zero retraces and zero cache
entries**: growth sizing reads host-side shapes the update already computed,
and the measured gather timing wraps a collective that already runs —
proven by the jaxpr bit-identity and ``cache_stats`` delta tests in
``test_gathers.py``.

Quick tour::

    from torchmetrics_tpu import observability as obs
    from torchmetrics_tpu.observability import gathers

    obs.enable()
    gathers.enable_gather_telemetry()     # or TM_TPU_GATHER_TELEMETRY=1
    acc = DeferredRaggedSync(map_metric, mesh=mesh)
    ...                                   # update steps are sized live
    map_metric.telemetry.as_dict()["gathers"]   # growth rows + watermark
    gathers.project_gather_bytes(64)      # pod-scale flat projection
    advice = gathers.GatherAdvisor().advise()
    advice["candidates"][0]               # biggest projected consumer
    obs.export(gathers.gather_report(), fmt="jsonl")

A cheap, device-free example (the doctest tier-1 actually runs) — two steps
of BENCH_r05's mAP workload at 85,760 cat bytes/step project to exactly the
archived 5,402,880 bytes/chip/step at 64 chips, and the advisor names the
sketch route first::

    >>> from torchmetrics_tpu.observability.gathers import (
    ...     GatherAdvisor, project_gather_bytes)
    >>> rows = {"MeanAveragePrecision#0": {
    ...     "class": "MeanAveragePrecision",
    ...     "gathers": {"steps": 2, "cat_elements": 13440,
    ...                 "cat_bytes": 171520, "ew_bytes_per_step": 85760.0,
    ...                 "hwm_bytes": 171520, "leaves": {}}}}
    >>> proj = project_gather_bytes(64, report={"metrics": rows})
    >>> proj["metrics"]["MeanAveragePrecision#0"]["projected_bytes_per_chip_per_step"]
    5402880
    >>> advice = GatherAdvisor(n_chips=64).advise(report={"metrics": rows})
    >>> advice["candidates"][0]["recommendation"]
    'sketch-first'
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

import jax

from torchmetrics_tpu.observability import registry
from torchmetrics_tpu.utilities.benchmark import (
    RING_GRANULE_BYTES,
    _is_psum_shaped,
    tiled_allgather_bytes,
    two_stage_gather_bytes,
)

__all__ = [
    "GATHER_LEDGER_KIND",
    "GATHER_REPORT_KIND",
    "GatherAdvisor",
    "SKETCH_ALTERNATIVES",
    "cat_growth_rows",
    "disable_gather_telemetry",
    "enable_gather_telemetry",
    "gather_report",
    "gather_telemetry_enabled",
    "project_gather_bytes",
    "sketch_alternative_for",
]

_log = logging.getLogger("torchmetrics_tpu.observability")

#: ``kind`` stamp on every advisor ledger entry (JSONL consumers filter on it
#: exactly like ``sharding_decision`` / ``autotune_decision``)
GATHER_LEDGER_KIND = "gather_advice"
#: ``kind`` stamp on the front-door report payload
GATHER_REPORT_KIND = "gather_report"

#: The sketch layer's existing fixed-shape alternatives, by base metric name
#: (Binary/Multiclass/Multilabel prefixes are stripped by
#: :func:`sketch_alternative_for`).  Each alternative replaces an unbounded
#: cat state with a fixed-shape state that rides the psum family — per-step
#: gather bytes drop to zero.
SKETCH_ALTERNATIVES: Dict[str, str] = {
    "AUROC": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "AveragePrecision": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "PrecisionRecallCurve": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
    "ROC": (
        "thresholds=N binned mode: fixed-shape confmat state rides the psum "
        "family instead of gathering raw scores"
    ),
}


def sketch_alternative_for(cls_name: str) -> Optional[str]:
    """The sketch layer's fixed-shape alternative for metric class
    ``cls_name``, or ``None`` when none ships yet (mAP, ROUGE — ROADMAP
    open item 5's sketch-backed variants)."""
    base = cls_name
    for prefix in ("Binary", "Multiclass", "Multilabel"):
        if base.startswith(prefix):
            base = base[len(prefix) :]
            break
    return SKETCH_ALTERNATIVES.get(base)


# ---------------------------------------------------------------------------
# layer 1: live cat-state growth sizing
# ---------------------------------------------------------------------------


def _leaf_sizes(leaf: Any) -> Tuple[int, int]:
    """``(elements, bytes)`` of one state leaf's unpadded items — the same
    per-item ``size * itemsize`` accounting ``split_state_bytes`` uses, so
    live growth rows reconcile exactly with the bench's analytic tables."""
    elements = nbytes = 0
    for v in jax.tree.leaves(leaf):
        size = int(getattr(v, "size", 1))
        dtype = getattr(v, "dtype", None)
        itemsize = int(getattr(dtype, "itemsize", 8))
        elements += size
        nbytes += size * itemsize
    return elements, nbytes


def cat_growth_rows(
    metric: Any,
    partial_states: Iterable[Mapping[str, Any]],
    accumulated_states: Optional[Iterable[Mapping[str, Any]]] = None,
) -> Dict[str, Dict[str, int]]:
    """Size one update step's gather-family growth for ``metric``.

    ``partial_states`` holds this step's freshly-updated per-device states;
    ``accumulated_states`` (when given) the running per-device states after
    the merge.  For every leaf in ``metric._reductions`` that syncs by
    gather (cat/None/callable/structural — everything
    ``_is_psum_shaped`` excludes), returns the *unpadded* appended
    ``{"elements", "bytes"}`` summed over all devices' partials — matching
    the whole-update ``cat_state_bytes_per_step`` accounting bench.py's
    ``state_reduce_bytes_table`` archives — plus ``total_bytes`` (the
    running cat size, for the high-watermark) from the accumulated states.

    Pure host-side sizing: reads shapes/dtypes only, never device buffers,
    so feeding the registry from an update loop cannot retrace anything.
    """
    reductions = getattr(metric, "_reductions", None) or {}
    partials = list(partial_states)
    accumulated = list(accumulated_states) if accumulated_states is not None else None
    rows: Dict[str, Dict[str, int]] = {}
    for name, reduce in sorted(reductions.items()):
        if _is_psum_shaped(reduce):
            continue
        elements = nbytes = 0
        for st in partials:
            if name not in st:
                continue
            e, b = _leaf_sizes(st[name])
            elements += e
            nbytes += b
        row = {"elements": elements, "bytes": nbytes}
        if accumulated is not None:
            total = 0
            for st in accumulated:
                if name in st:
                    total += _leaf_sizes(st[name])[1]
            row["total_bytes"] = total
        rows[name] = row
    return rows


# ---------------------------------------------------------------------------
# arming (the second half of the double gate)
# ---------------------------------------------------------------------------


def enable_gather_telemetry() -> None:
    """Arm the gather plane: live cat-state growth accounting in
    ``DeferredRaggedSync.update`` plus block-until-ready measured timing of
    the deferred ragged gather.

    Nothing records until ``observability.enable()`` is also on.  Arming
    changes no cache key and adds no retrace: growth sizing reads host-side
    shapes the update already computed, and the measured timing waits on a
    collective that already runs (the wait is observation cost at the host
    boundary, not graph change)."""
    registry.set_gather_armed(True)


def disable_gather_telemetry() -> None:
    """Disarm the gather plane.  Recorded growth rows and measured buckets
    are kept (clear them with ``reset_telemetry()``); new steps stop being
    sized and the gather stops being block-until-ready timed."""
    registry.set_gather_armed(False)


def gather_telemetry_enabled() -> bool:
    """True while the gather plane is armed (the registry gate)."""
    return registry.gather_armed()


# ---------------------------------------------------------------------------
# layer 2: pod-scale projection
# ---------------------------------------------------------------------------


def _gather_rows(report: Optional[Mapping[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """``{label: {"class", "gathers"}}`` for every metric row carrying live
    cat-growth attribution, from ``report`` (default: the live registry)."""
    rep = report if report is not None else registry.report()
    out: Dict[str, Dict[str, Any]] = {}
    for label, row in rep.get("metrics", {}).items():
        g = row.get("gathers")
        if isinstance(g, Mapping) and int(g.get("steps", 0)) > 0:
            out[label] = {"class": row.get("class", label), "gathers": g}
    return out


def project_gather_bytes(
    n_chips: int, report: Optional[Mapping[str, Any]] = None
) -> Dict[str, Any]:
    """Extrapolate live cat-state attribution to an ``n_chips`` mesh with
    the flat all-gather model: each chip receives every other chip's
    per-step cat shard, so per-chip traffic is
    ``(n_chips - 1) x mean bytes/step``.

    ``report`` defaults to the live registry report; pass an archived one to
    project old runs.  Under BENCH_r05's mAP workload (85,760 cat
    bytes/step) this reproduces the archive's 5,402,880 bytes/chip/step at
    64 chips exactly — the exact-figure contract ``test_gathers.py`` and the
    bench's gather leg both assert.

    Returns per-metric rows (mean ``bytes_per_step``, the EW growth rate,
    per-leaf projections) plus ``total_bytes_per_chip_per_step``.
    """
    n = int(n_chips)
    metrics: Dict[str, Dict[str, Any]] = {}
    total = 0
    for label, row in sorted(_gather_rows(report).items()):
        g = row["gathers"]
        steps = max(int(g["steps"]), 1)
        bps = int(round(int(g["cat_bytes"]) / steps))
        projected = max(n - 1, 0) * bps
        leaves = {}
        for name, leaf in sorted(dict(g.get("leaves", {})).items()):
            lsteps = max(int(leaf.get("steps", steps)), 1)
            lbps = int(round(int(leaf.get("bytes", 0)) / lsteps))
            leaves[name] = {
                "bytes_per_step": lbps,
                "projected_bytes_per_chip_per_step": max(n - 1, 0) * lbps,
            }
        metrics[label] = {
            "class": row["class"],
            "steps": int(g["steps"]),
            "bytes_per_step": bps,
            "ew_bytes_per_step": float(g.get("ew_bytes_per_step", 0.0)),
            "hwm_bytes": int(g.get("hwm_bytes", 0)),
            "projected_bytes_per_chip_per_step": projected,
            "leaves": leaves,
        }
        total += projected
    return {
        "n_chips": n,
        "model": "flat",
        "metrics": metrics,
        "total_bytes_per_chip_per_step": total,
    }


# ---------------------------------------------------------------------------
# layer 3: report-only advice
# ---------------------------------------------------------------------------


class GatherAdvisor:
    """Report-only advisor ranking cat-state consumers by projected
    pod-scale gather bytes.

    For each metric with live cat-growth attribution, :meth:`advise`
    projects the flat all-gather cost at ``n_chips`` (linear in chip count —
    the MLPerf pod paper's scaling cap, arxiv 1909.09756) and models both
    escape hatches:

    * ``two_stage`` — gather over ICI inside each host, exchange one
      aggregated copy per host over DCN
      (``utilities.benchmark.two_stage_gather_bytes``): cross-host bytes
      scale with hosts, not chips, an ``~n_local_devices x`` DCN cut;
    * ``sketch`` — replace the cat leaf with a fixed-shape sketch state that
      rides the psum family: per-step gather bytes drop to zero.  Where the
      sketch layer already ships the alternative (AUROC / AveragePrecision /
      ROC / PrecisionRecallCurve ``thresholds=N`` binned modes) the advisor
      quotes it by name; for mAP/ROUGE the recommendation points at ROADMAP
      open item 5's sketch-backed variants.

    Candidates at or above ``sketch_first_bytes`` projected flat bytes are
    recommended ``"sketch-first"`` (the two-stage route still moves every
    byte once — only a sketch caps the linear-in-steps growth); smaller
    consumers get ``"two-stage"``.  Advice never touches metric config:
    actuation is ROADMAP open item 5.  Every :meth:`advise` lands in
    :meth:`decision_ledger` as a ``kind: "gather_advice"`` row and mirrors
    into the flight recorder's ``gather`` category when armed.
    """

    def __init__(
        self,
        n_chips: int = 64,
        n_local_devices: int = 8,
        granule: int = RING_GRANULE_BYTES,
        sketch_first_bytes: int = 1 << 20,
    ) -> None:
        self.n_chips = int(n_chips)
        #: chips per host in the projected mesh (v4-8 host granularity);
        #: hosts = ceil(n_chips / n_local_devices)
        self.n_local_devices = max(int(n_local_devices), 1)
        self.granule = int(granule)
        #: projected flat bytes/chip/step at/above this make the candidate
        #: sketch-first: two-stage still ships every byte once per step,
        #: only a fixed-shape sketch kills the linear-in-steps growth
        self.sketch_first_bytes = int(sketch_first_bytes)
        self._seq = 0
        self._ledger: List[Dict[str, Any]] = []

    def advise(
        self,
        report: Optional[Mapping[str, Any]] = None,
        n_chips: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rank every live cat-state consumer by projected pod-scale bytes.

        ``report`` defaults to the live registry report (pass an archived
        one to re-advise old runs); ``n_chips`` defaults to the advisor's.
        """
        n = int(n_chips or self.n_chips)
        n_local = min(self.n_local_devices, n)
        n_hosts = max(1, -(-n // n_local))
        candidates: List[Dict[str, Any]] = []
        total_flat = total_two_stage = 0
        for label, row in sorted(_gather_rows(report).items()):
            g = row["gathers"]
            steps = max(int(g["steps"]), 1)
            bps = int(round(int(g["cat_bytes"]) / steps))
            if bps <= 0:
                continue
            flat = max(n - 1, 0) * bps
            tiled = int(tiled_allgather_bytes(bps, n, self.granule))
            stages = two_stage_gather_bytes(bps, n_hosts, n_local, self.granule)
            alternative = sketch_alternative_for(str(row["class"]))
            recommendation = (
                "sketch-first" if flat >= self.sketch_first_bytes else "two-stage"
            )
            candidates.append(
                {
                    "metric": label,
                    "class": row["class"],
                    "steps": int(g["steps"]),
                    "bytes_per_step": bps,
                    "ew_bytes_per_step": float(g.get("ew_bytes_per_step", 0.0)),
                    "hwm_bytes": int(g.get("hwm_bytes", 0)),
                    "projected_flat_bytes_per_chip_per_step": flat,
                    "projected_tiled_bytes_per_chip_per_step": tiled,
                    "two_stage_dcn_bytes_per_chip_per_step": stages["two_stage"],
                    "two_stage_ici_bytes_per_chip_per_step": stages["ici"],
                    "two_stage_cut_bytes_per_chip_per_step": stages["flat"]
                    - stages["two_stage"],
                    # a sketch state is fixed-shape psum: the whole projected
                    # gather cost goes away, bounded-error attested
                    "sketch_cut_bytes_per_chip_per_step": flat,
                    "sketch_alternative": alternative,
                    "recommendation": recommendation,
                }
            )
            total_flat += flat
            total_two_stage += stages["two_stage"]
        candidates.sort(
            key=lambda c: (-c["projected_flat_bytes_per_chip_per_step"], c["metric"])
        )
        advice = {
            "kind": GATHER_LEDGER_KIND,
            "seq": self._seq,
            "n_chips": n,
            "n_hosts": n_hosts,
            "n_local_devices": n_local,
            "granule_bytes": self.granule,
            "sketch_first_bytes": self.sketch_first_bytes,
            "total_projected_flat_bytes_per_chip_per_step": total_flat,
            "total_two_stage_dcn_bytes_per_chip_per_step": total_two_stage,
            "candidates": candidates,
            "recommended": [
                f"{c['metric']}: {c['recommendation']}" for c in candidates
            ],
            "note": (
                "report-only: cat states stay raw until open item 5's "
                "sketch-backed variants / two-stage ragged topology land; "
                "candidates ranked by projected flat bytes/chip/step"
            ),
        }
        self._seq += 1
        self._ledger.append(advice)
        if candidates:
            top = candidates[0]
            registry.gather_trace(
                top["metric"],
                "advice",
                {
                    "seq": advice["seq"],
                    "n_chips": n,
                    "recommendation": top["recommendation"],
                    "projected_flat_bytes_per_chip_per_step": top[
                        "projected_flat_bytes_per_chip_per_step"
                    ],
                    "candidates": len(candidates),
                },
            )
        import copy

        return copy.deepcopy(advice)

    def decision_ledger(self) -> List[Dict[str, Any]]:
        """Every advice payload this advisor produced, oldest first —
        stable schema (``kind == "gather_advice"``), safe to mutate."""
        import copy

        return copy.deepcopy(self._ledger)

    def export_ledger(
        self, path: Optional[str] = None, stream: Optional[Any] = None
    ) -> List[str]:
        """Write the ledger through the export front door: one JSONL line
        per advice, stamped with ``schema_version`` + process identity and
        parseable back via ``observability.parse_export_line`` — the same
        contract as ``ShardingAdvisor.export_ledger``."""
        from torchmetrics_tpu.observability.export import JSONLinesExporter

        exporter = JSONLinesExporter(path=path, stream=stream)
        return [exporter.export(entry) for entry in self._ledger]


# ---------------------------------------------------------------------------
# the front-door report
# ---------------------------------------------------------------------------


def gather_report(
    n_chips: Iterable[int] = (8, 16, 64),
    advise_at: Optional[int] = 64,
    report: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    """One ``kind: "gather_report"`` payload tying all three layers
    together, ready for ``observability.export`` (the JSONL line parses back
    through ``parse_export_line``; the Prometheus exporter renders the
    ``tm_tpu_gather_*`` families from it).

    Layout::

        {"schema": 1, "kind": "gather_report", "armed": bool,
         "gather": {
            "metrics": {label: gathers-block, ...},   # live growth rows
            "projection": {"8": ..., "16": ..., "64": ...},
            "advice": {...}}}                         # iff advise_at

    ``n_chips`` picks the projected mesh sizes; ``advise_at`` the mesh the
    advisor ranks against (``None`` skips advice).
    """
    rep = report if report is not None else registry.report()
    payload: Dict[str, Any] = {
        "schema": 1,
        "kind": GATHER_REPORT_KIND,
        "armed": gather_telemetry_enabled(),
        "enabled": registry.enabled(),
        "gather": {
            "metrics": {
                label: dict(row["gathers"])
                for label, row in sorted(_gather_rows(rep).items())
            },
            "projection": {
                str(int(n)): project_gather_bytes(int(n), report=rep)
                for n in n_chips
            },
        },
    }
    if advise_at is not None:
        payload["gather"]["advice"] = GatherAdvisor(n_chips=int(advise_at)).advise(
            report=rep
        )
    return payload


# honour TM_TPU_GATHER_TELEMETRY=1 the way registry honours TM_TPU_TELEMETRY
if os.environ.get("TM_TPU_GATHER_TELEMETRY", "").strip().lower() in (
    "1",
    "true",
    "on",
    "yes",
):  # pragma: no cover - env-driven path
    enable_gather_telemetry()
