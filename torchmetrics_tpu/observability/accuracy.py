"""Accuracy attestation plane: value provenance, an error-budget ledger, and
shadow-exact audits.

The four planes before this one (telemetry, flight recorder, fleet,
memory/cost) attribute *cost*; this module attributes *accuracy*.  The
library ships three sanctioned sources of inexactness — sketch states with
data-dependent bounds (PR 7), int8/bf16 compressed collectives with
predicted quantization bounds (PR 8), and quarantine-degraded quorums
(PR 14) — whose bounds are declared or statically predicted but, until now,
never stamped onto the values they affect nor verified at runtime.  Three
layers close that gap:

1. **Value attestations** — every ``Metric.compute()`` can emit a
   :class:`ValueAttestation`: the composed worst-case error bound of the
   reported value plus its full provenance chain (sketch grid geometry and
   the data-dependent ``auc_error_bound`` where a curve histogram exists,
   the committed ``SyncPolicy``'s compression mode with the predicted quant
   bound from ``parallel/compress.py``, the surviving quorum fraction from
   the schema-1.6 ``quorum`` block, the cadence policy, and the 12-hex
   config fingerprint).  Attestations of *approximate* values land in the
   telemetry registry (schema 1.7's ``attestation`` block), export as JSONL
   kind ``"attestation"`` and ``tm_tpu_accuracy_*`` Prometheus families,
   and mirror into the flight recorder's ``accuracy`` category.  Exact-path
   metrics attest ``exact=True`` with a zero bound — and deliberately leave
   the registry row untouched, so unapproximated reports stay byte-identical
   to schema 1.6.
2. **Error-budget ledger** — declared budgets (``approx_error``,
   ``SyncPolicy.error_budget``) become a burn ledger: each provenance source
   reports its predicted bound against its declared budget, and a latched
   :class:`~torchmetrics_tpu.observability.health.AccuracyBudgetRule` fires
   when the composed bound exceeds the declared budget (e.g. sketch eps
   stacked on an int8 sync).
3. **Shadow-exact audits** — a :class:`ShadowAuditor` keeps an exact twin of
   an approximate/compressed metric, feeds it a *deterministic* sample of
   update batches (seeded hash of a caller-supplied step index — no
   wallclock, no RNG), and measures the *observed* ``|approx - exact|``
   against the *predicted* bound.  Observed > predicted raises a
   severity-critical health alert; wire the alert into
   ``SyncAutotuner.guardrail_sink()`` and an out-of-budget compression
   commit is vetoed or rolled back automatically.

Everything is double-gated: :func:`enable_accuracy_telemetry` (or
``TM_TPU_ACCURACY_TELEMETRY=1``) arms the plane, but nothing records until
``observability.enable()`` is also on.  Arming adds **zero retraces and zero
cache entries** on the primary update path: attestation reads only host-side
config and telemetry (never traced values), and the shadow twin is a
separate instance that owns its own cache entries.  Proven by the jaxpr
bit-identity and ``cache_stats`` delta tests in ``test_accuracy.py``.

Quick tour::

    from torchmetrics_tpu import observability as obs
    from torchmetrics_tpu.observability import accuracy

    obs.enable()
    accuracy.enable_accuracy_telemetry()   # or TM_TPU_ACCURACY_TELEMETRY=1
    auroc = BinaryAUROC(approx="sketch")
    ...                                    # train
    auroc.compute()                        # attests itself into the registry
    auroc.telemetry.as_dict()["attestation"]["bound"]
    obs.export(accuracy.accuracy_report([auroc]), fmt="jsonl")

    auditor = accuracy.ShadowAuditor(auroc, exact_twin, sample_rate=1 / 64,
                                     sinks=[tuner.guardrail_sink()])
    auditor.update(preds, target, step=step)   # twin sees a seeded sample
    auditor.audit(step=step)                   # breach -> alert -> rollback

A cheap, device-free example (the doctest tier-1 actually runs)::

    >>> from torchmetrics_tpu.sketches.quantile import QuantileSketch
    >>> from torchmetrics_tpu.observability.accuracy import compose_sources
    >>> row = QuantileSketch(bins=200).provenance()
    >>> bound, ledger = compose_sources([row])
    >>> round(bound, 6)
    0.005
    >>> ledger[0]["source"]
    'sketch'
"""

from __future__ import annotations

import hashlib
import logging
import math
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

import numpy as np

from torchmetrics_tpu.observability import registry

__all__ = [
    "ShadowAuditor",
    "ValueAttestation",
    "accuracy_report",
    "accuracy_telemetry_enabled",
    "attest",
    "compose_sources",
    "disable_accuracy_telemetry",
    "enable_accuracy_telemetry",
    "shadow_sampled",
]

_log = logging.getLogger("torchmetrics_tpu.observability")


# ---------------------------------------------------------------------------
# layer 1: provenance composition
# ---------------------------------------------------------------------------


def _committed_policy(metric: Any) -> Optional[Any]:
    """The ``SyncPolicy`` the autotuner committed onto ``metric``, if any —
    the same ``__dict__`` slot ``parallel/autotune.py`` installs (read
    directly so the plane never imports the tuner)."""
    d = getattr(metric, "__dict__", None)
    return d.get("_autotuned_policy") if isinstance(d, dict) else None


def _sketch_source(metric: Any) -> Optional[Dict[str, Any]]:
    """Sketch provenance: grid geometry plus the data-dependent AUC bound
    when the metric holds a ``(*prefix, 2, bins + 1)`` curve histogram."""
    sketch = getattr(metric, "_sketch", None)
    if sketch is None:
        return None
    hist = None
    state = getattr(metric, "_state", None)
    if isinstance(state, Mapping):
        leaf = state.get("score_hist")
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) >= 2 and shape[-2:] == (2, sketch.n_cells):
            hist = leaf
    row = sketch.provenance(hist)
    row["budget"] = getattr(metric, "approx_error", None)
    return row


def _gather_approx_source(metric: Any) -> Optional[Dict[str, Any]]:
    """Gather-family approximation provenance (``approx="sketch"`` mAP,
    ``approx="reservoir"`` text corpora): the metric itself owns the
    data-dependent bound derivation, so the plane only asks for the row via
    the ``_gather_approx_provenance`` hook and stamps the declared
    ``approx_error`` as its budget.  Never raises — a hook failure simply
    drops the source (the attestation stays conservative elsewhere)."""
    hook = getattr(metric, "_gather_approx_provenance", None)
    if hook is None:
        return None
    try:
        row = hook()
    except Exception:
        _log.debug("gather_approx provenance failed for %r", metric, exc_info=True)
        return None
    if not row:
        return None
    row = dict(row)
    row["source"] = "gather_approx"
    row.setdefault("budget", getattr(metric, "approx_error", None))
    return row


def _compression_source(metric: Any, policy: Any) -> Optional[Dict[str, Any]]:
    if policy is None or policy.compression in (None, "none"):
        return None
    from torchmetrics_tpu.parallel.compress import compression_bound_provenance

    return compression_bound_provenance(policy.compression, budget=policy.error_budget)


def _quorum_source(metric: Any, n_devices: Optional[int]) -> Optional[Dict[str, Any]]:
    """Quorum provenance: a degraded quorum is *sample-loss* provenance, not
    an error bound — the surviving replicas' contributions are exact — so the
    row carries ``bound`` 0 and names the fraction instead."""
    t = registry.telemetry_for(metric, create=False)
    quorum = t.quorum if t is not None else None
    if quorum is None:
        try:
            from torchmetrics_tpu.resilience.quarantine import degradation_report, is_degraded

            if not is_degraded(metric):
                return None
            quorum = degradation_report(metric, n_devices=n_devices)
        except Exception:
            return None
    row: Dict[str, Any] = {
        "source": "quorum",
        "bound": 0.0,
        "quarantined": len(quorum.get("quarantined", ())),
    }
    if quorum.get("quorum_fraction") is not None:
        row["quorum_fraction"] = float(quorum["quorum_fraction"])
    elif n_devices:
        row["quorum_fraction"] = (int(n_devices) - row["quarantined"]) / int(n_devices)
    return row


def compose_sources(
    sources: Iterable[Mapping[str, Any]],
) -> Tuple[float, List[Dict[str, Any]]]:
    """Fold provenance source rows into ``(composed_bound, ledger)``.

    The composed worst-case bound is the *sum* of the per-source bounds
    (approximation stages stack — a sketch eps on top of an int8 sync can at
    worst add).  Each ledger row restates the source's bound against its
    declared budget as a burn fraction; a missing budget leaves
    ``within_budget`` at ``None`` rather than guessing.
    """
    bound = 0.0
    ledger: List[Dict[str, Any]] = []
    for src in sources:
        b = float(src.get("bound", 0.0))
        bound += b
        budget = src.get("budget")
        row: Dict[str, Any] = {"source": str(src.get("source", "?")), "bound": b, "budget": budget}
        if budget is not None and float(budget) > 0.0:
            row["burn"] = b / float(budget)
            row["within_budget"] = b <= float(budget)
        else:
            row["within_budget"] = None
        ledger.append(row)
    return bound, ledger


class ValueAttestation:
    """The accuracy contract of one computed value: the composed worst-case
    error bound, the provenance chain it came from, and the burn ledger of
    every declared budget.  ``exact`` is True iff no approximation source is
    active — a zero bound with an empty chain."""

    __slots__ = (
        "label",
        "cls",
        "fingerprint",
        "exact",
        "bound",
        "sources",
        "ledger",
        "policy",
        "quorum_fraction",
        "within_budget",
        "observed_err",
        "step",
        "sharding",
    )

    def __init__(
        self,
        label: str,
        cls: str,
        fingerprint: Optional[str],
        sources: List[Dict[str, Any]],
        policy: Optional[Dict[str, Any]] = None,
        step: Optional[int] = None,
        sharding: Optional[Dict[str, int]] = None,
    ) -> None:
        self.label = label
        self.cls = cls
        self.fingerprint = fingerprint
        self.sources = list(sources)
        self.policy = dict(policy) if policy else None
        #: installed ``state_sharding`` specs (``{leaf: shard_axis}``) —
        #: provenance only: reduce-scatter sync is bit-for-bit exact, so
        #: sharding never contributes an approximation source or bound
        self.sharding = dict(sharding) if sharding else None
        self.step = None if step is None else int(step)
        self.bound, self.ledger = compose_sources(self.sources)
        self.exact = not self.sources
        self.quorum_fraction = next(
            (s.get("quorum_fraction") for s in self.sources if s.get("source") == "quorum"),
            None,
        )
        judged = [r["within_budget"] for r in self.ledger if r["within_budget"] is not None]
        self.within_budget = all(judged) if judged else None
        #: measured ``|approx - exact|`` from the latest shadow audit, if one ran
        self.observed_err: Optional[float] = None

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": "attestation",
            "label": self.label,
            "class": self.cls,
            "fingerprint": self.fingerprint,
            "exact": self.exact,
            "bound": self.bound,
            "sources": [dict(s) for s in self.sources],
            "ledger": [dict(r) for r in self.ledger],
            "within_budget": self.within_budget,
        }
        if self.policy is not None:
            out["policy"] = dict(self.policy)
        if self.sharding is not None:
            out["sharding"] = dict(self.sharding)
        if self.quorum_fraction is not None:
            out["quorum_fraction"] = self.quorum_fraction
        if self.observed_err is not None:
            out["observed_err"] = float(self.observed_err)
        if self.step is not None:
            out["step"] = self.step
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        tag = "exact" if self.exact else f"bound={self.bound:.3g}"
        return f"ValueAttestation({self.label}, {tag}, sources={len(self.sources)})"


def attest(
    metric: Any,
    *,
    step: Optional[int] = None,
    n_devices: Optional[int] = None,
) -> ValueAttestation:
    """Compose ``metric``'s :class:`ValueAttestation` from host-side config
    and telemetry alone — sketch geometry, committed sync policy, quorum
    block, config fingerprint.  Never reads traced values and never touches
    compiled code, so calling it (or having ``compute()`` call it while the
    plane is armed) cannot change a cache key or add a retrace."""
    t = registry.telemetry_for(metric, create=False)
    label = t.label if t is not None else type(metric).__name__
    fingerprint = None
    try:
        from torchmetrics_tpu.core.compile import _fingerprint_hash, config_fingerprint

        fingerprint = _fingerprint_hash(config_fingerprint(metric))
    except Exception:
        _log.debug("config fingerprint failed for %r", metric, exc_info=True)
    policy = _committed_policy(metric)
    policy_block = None
    if policy is not None:
        policy_block = {
            "every_n": None if policy.at_compute else policy.every_n_steps,
            "at_compute": bool(policy.at_compute),
            "compression": policy.compression,
            "error_budget": policy.error_budget,
        }
    sources = [
        src
        for src in (
            _sketch_source(metric),
            _gather_approx_source(metric),
            _compression_source(metric, policy),
            _quorum_source(metric, n_devices),
        )
        if src is not None
    ]
    shardings = getattr(metric, "_state_shardings", None) or None
    sharding_block = (
        {name: int(spec.axis) for name, spec in sorted(shardings.items())}
        if shardings
        else None
    )
    return ValueAttestation(
        label,
        type(metric).__name__,
        fingerprint,
        sources,
        policy=policy_block,
        step=step,
        sharding=sharding_block,
    )


def _attest_and_record(metric: Any) -> None:
    """The registry's installed attestor: compose and stamp (approximate
    values only — :func:`registry.record_attestation` clears the slot for
    exact attestations, keeping unapproximated reports byte-identical)."""
    registry.record_attestation(metric, attest(metric).as_dict())


# ---------------------------------------------------------------------------
# arming (the second half of the double gate)
# ---------------------------------------------------------------------------


def enable_accuracy_telemetry() -> None:
    """Arm the accuracy plane: every ``Metric.compute()`` /
    ``MetricCollection.compute()`` attests its value into the registry.

    Nothing records until ``observability.enable()`` is also on.  Arming
    changes no cache key and adds no retrace: attestation reads host-side
    config/telemetry outside traced code."""
    registry.set_accuracy_attestor(_attest_and_record)
    registry.set_accuracy_armed(True)


def disable_accuracy_telemetry() -> None:
    """Disarm the accuracy plane.  Recorded attestations are kept (clear
    them with ``reset_telemetry()``); new computes stop attesting."""
    registry.set_accuracy_armed(False)


def accuracy_telemetry_enabled() -> bool:
    """True while the accuracy plane is armed (the registry gate)."""
    return registry.accuracy_armed()


# ---------------------------------------------------------------------------
# layer 3: shadow-exact audits
# ---------------------------------------------------------------------------


def shadow_sampled(step: int, *, sample_rate: float, seed: int = 0) -> bool:
    """Deterministically decide whether ``step`` is in the shadow sample.

    A seeded SHA-256 of the caller-supplied step index, mapped to ``[0, 1)``
    and compared against ``sample_rate`` — no wallclock, no RNG state, so the
    same (seed, step) samples identically on every host and every rerun."""
    digest = hashlib.sha256(f"{int(seed)}:{int(step)}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2.0**64 < sample_rate


class ShadowAuditor:
    """Exact twin + deterministic sampling + observed-vs-predicted audits.

    ``metric`` is the approximate/compressed primary; ``exact_twin`` is an
    exact-path instance of the same metric (the caller constructs it —
    switching a sketch config back to exact is a construction-time decision
    the auditor cannot deep-copy its way to).  ``update(..., step=N)``
    always updates the primary and, on a :func:`shadow_sampled` step, the
    twin; :meth:`audit` computes both and measures the observed
    ``|approx - exact|`` (max over result leaves, absolute and relative)
    against the predicted composed bound.

    Observed > predicted raises a severity-``critical``
    :class:`~torchmetrics_tpu.observability.health.Alert` through every
    configured sink.  Pass ``tuner.guardrail_sink()`` as a sink and the
    :class:`~torchmetrics_tpu.parallel.autotune.SyncAutotuner` vetoes a
    trialling commit or rolls back a committed one — the audit closes the
    PR 11 loop with *measured* error.  Audits also fold the observed
    relative error into the primary's telemetry (the ``attestation`` slot's
    ``observed_err``, plus the compressed bucket's ``quant_rel_err`` row
    when a compression policy is committed) so ``SyncAdvisor.recommend``
    and the fleet skew axis see it.

    The primary's update path is untouched: the twin is a separate instance
    owning its own compile-cache entries, and sampling is one hash on the
    host.  Zero retraces on the primary by construction (proven in
    ``test_accuracy.py``).
    """

    def __init__(
        self,
        metric: Any,
        exact_twin: Any,
        *,
        sample_rate: float = 1.0 / 16.0,
        seed: int = 0,
        predicted_bound: Optional[float] = None,
        sinks: Optional[List[Any]] = None,
        series: Optional[str] = None,
    ) -> None:
        if not (0.0 < sample_rate <= 1.0):
            raise ValueError(f"sample_rate must be in (0, 1], got {sample_rate}")
        if exact_twin is metric:
            raise ValueError("exact_twin must be a distinct instance, not the metric itself")
        self.metric = metric
        self.twin = exact_twin
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        #: explicit override of the composed predicted bound; ``None`` means
        #: every audit re-composes :func:`attest` (so a policy change between
        #: audits is judged against its own bound)
        self.predicted_bound = predicted_bound
        self.sinks: List[Any] = list(sinks) if sinks else []
        self.series = series if series is not None else f"accuracy/{type(metric).__name__}"
        self._updates = 0
        self._sampled = 0
        self._audits = 0
        self._breaches = 0
        self._last: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- feeding
    def sampled(self, step: int) -> bool:
        return shadow_sampled(step, sample_rate=self.sample_rate, seed=self.seed)

    def update(self, *args: Any, step: int, **kwargs: Any) -> bool:
        """Update the primary (always) and the twin (on sampled steps).
        Returns whether the twin saw this batch."""
        self.metric.update(*args, **kwargs)
        self._updates += 1
        take = self.sampled(step)
        if take:
            self.twin.update(*args, **kwargs)
            self._sampled += 1
        return take

    # ------------------------------------------------------------- auditing
    @staticmethod
    def _observed_error(approx: Any, exact: Any) -> Tuple[float, float]:
        """``(abs_err, rel_err)`` over the result pytrees: max absolute leaf
        deviation, and the same normalized by the exact result's magnitude."""
        import jax

        abs_err = 0.0
        scale = 0.0
        for a, b in zip(jax.tree.leaves(approx), jax.tree.leaves(exact)):
            av = np.asarray(a, dtype=np.float64)
            bv = np.asarray(b, dtype=np.float64)
            if av.size == 0 or bv.size == 0 or av.shape != bv.shape:
                continue
            abs_err = max(abs_err, float(np.max(np.abs(av - bv))))
            scale = max(scale, float(np.max(np.abs(bv))))
        return abs_err, abs_err / max(scale, 1e-12)

    def audit(self, step: int = 0) -> Dict[str, Any]:
        """Compute both paths and judge observed against predicted.

        Returns the audit record; a breach additionally emits the critical
        alert through every sink and mirrors into the flight recorder."""
        attestation = attest(self.metric, step=step)
        predicted = (
            float(self.predicted_bound)
            if self.predicted_bound is not None
            else attestation.bound
        )
        abs_err, rel_err = self._observed_error(self.metric.compute(), self.twin.compute())
        observed = rel_err
        breach = observed > predicted and math.isfinite(observed)
        self._audits += 1
        record = {
            "step": int(step),
            "observed_abs": abs_err,
            "observed_rel": rel_err,
            "predicted_bound": predicted,
            "breach": breach,
            "sampled_updates": self._sampled,
            "updates": self._updates,
        }
        self._last = record
        # fold the measurement back into the plane: the attestation slot's
        # observed_err, and (under a committed compression policy) the
        # compressed sum bucket's quant_rel_err row the SyncAdvisor reads
        attestation.observed_err = observed
        registry.record_attestation(self.metric, attestation.as_dict())
        policy = _committed_policy(self.metric)
        if policy is not None and policy.compression not in (None, "none"):
            registry.record_quant_error(self.metric, "float32/sum", observed)
        registry.accuracy_trace(
            attestation.label,
            "audit_breach" if breach else "audit",
            {
                "observed_rel": observed,
                "predicted_bound": predicted,
                "step": int(step),
            },
        )
        if breach:
            self._breaches += 1
            from torchmetrics_tpu.observability.health import Alert

            alert = Alert(
                self.series,
                "shadow_audit",
                "critical",
                step,
                observed,
                f"observed error {observed:.3g} exceeds predicted bound "
                f"{predicted:.3g} (shadow-exact audit over {self._sampled} "
                f"sampled of {self._updates} update batches)",
                {
                    "observed_abs": abs_err,
                    "observed_rel": rel_err,
                    "predicted_bound": predicted,
                    "sample_rate": self.sample_rate,
                },
            )
            for sink in self.sinks:
                try:
                    sink.emit(alert)
                except Exception:  # a broken pager must not break the audit
                    _log.debug("shadow audit sink %r failed", sink, exc_info=True)
        return record

    # ------------------------------------------------------------- reading
    def report(self) -> Dict[str, Any]:
        return {
            "series": self.series,
            "sample_rate": self.sample_rate,
            "seed": self.seed,
            "updates": self._updates,
            "sampled_updates": self._sampled,
            "audits": self._audits,
            "breaches": self._breaches,
            "last": dict(self._last) if self._last else None,
        }


# ---------------------------------------------------------------------------
# the front-door report
# ---------------------------------------------------------------------------


def accuracy_report(
    metrics: Optional[Iterable[Union[Any, Tuple[str, Any]]]] = None,
    n_devices: Optional[int] = None,
    auditors: Optional[Iterable[ShadowAuditor]] = None,
) -> Dict[str, Any]:
    """One ``kind: "attestation"`` payload tying the plane together, ready
    for ``observability.export`` (the JSONL line parses back through
    ``parse_export_line``; the Prometheus exporter renders the
    ``tm_tpu_accuracy_*`` families from it).

    Layout::

        {"schema": 1, "kind": "attestation", "armed": bool, "enabled": bool,
         "accuracy": {
            "attestations": {label: attestation-dict, ...},
            "ledger": [{"label", "source", "bound", "budget", ...}, ...],
            "audits": [ShadowAuditor.report(), ...]}}      # iff given

    ``metrics`` (when given) attests those instances explicitly — including
    exact ones, which appear here with ``exact: true`` even though they never
    occupy a registry slot.  Without ``metrics``, the report carries whatever
    attestations the armed plane already stamped into the registry.
    """
    attestations: Dict[str, Any] = {}
    if metrics is not None:
        for item in metrics:
            if isinstance(item, tuple) and len(item) == 2 and isinstance(item[0], str):
                label, metric = item
                att = attest(metric, n_devices=n_devices).as_dict()
                att["label"] = label
            else:
                metric = item
                att = attest(metric, n_devices=n_devices).as_dict()
                label = att["label"]
            attestations[label] = att
    else:
        rep = registry.report()
        for label, row in rep.get("metrics", {}).items():
            if isinstance(row.get("attestation"), Mapping):
                attestations[label] = dict(row["attestation"])
    ledger = [
        {"label": label, **row}
        for label, att in sorted(attestations.items())
        for row in att.get("ledger", ())
    ]
    payload: Dict[str, Any] = {
        "schema": 1,
        "kind": "attestation",
        "armed": accuracy_telemetry_enabled(),
        "enabled": registry.enabled(),
        "accuracy": {"attestations": attestations, "ledger": ledger},
    }
    if auditors is not None:
        payload["accuracy"]["audits"] = [a.report() for a in auditors]
    return payload


# the attestor is harmless to install eagerly (it only runs once armed), and
# installing it here means arming via the registry flag alone also works
registry.set_accuracy_attestor(_attest_and_record)

# honour TM_TPU_ACCURACY_TELEMETRY=1 the way registry honours TM_TPU_TELEMETRY
if os.environ.get("TM_TPU_ACCURACY_TELEMETRY", "").strip().lower() in (
    "1",
    "true",
    "on",
    "yes",
):  # pragma: no cover - env-driven path
    enable_accuracy_telemetry()
