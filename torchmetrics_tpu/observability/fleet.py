"""Fleet telemetry plane: cross-host aggregation of per-process registry
snapshots.

Every surface in ``observability/`` so far — the registry report, the flight
recorder, the Prometheus/JSONL exporters — is strictly host-local.  On a
multi-host pod that leaves an operator with one disjoint exposition per
process and no answer to "which host is slow?".  This module closes the gap:

* :func:`gather_reports` ships each process's :func:`registry.report`
  snapshot across DCN (one allgather for the lengths, one for the padded
  JSON payloads) and hands every process the full per-process list.
* :class:`FleetView` merges those snapshots into one pod-global report:
  counters sum exactly, the fixed-bucket :class:`registry.SpanStats`
  histograms merge elementwise, compile-cache stats sum, and the
  per-process originals are retained under ``per_process``.
* :meth:`FleetView.skew` attributes per-replica imbalance: max/median/min of
  the measured sync-wait digests (``record_sync_wait``), byte and retrace
  skew, and the straggler process by name — the report
  :class:`parallel.coalesce.SyncAdvisor` folds in via ``recommend(fleet=)``.

Multi-host behavior is tier-1 testable on CPU through the same injectable
``n_processes``/``allgather`` seam :func:`parallel.coalesce.coalesced_host_sync`
uses; with one process everything collapses to the identity —
:func:`fleet_report` returns the local :func:`registry.report` unchanged.

Nothing here touches a traced graph: gathering runs eagerly at the host
boundary on plain ``uint8`` payloads, so building a fleet view can never
change a cache key or add a retrace.
"""

from __future__ import annotations

import json
import statistics
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.observability.registry import aggregate_telemetry, report as _local_report
from torchmetrics_tpu.utilities.prints import rank_zero_warn

__all__ = [
    "FleetView",
    "fleet_report",
    "gather_reports",
    "process_count",
    "process_index",
    "sync_wait_digest",
]


def process_index() -> int:
    """``jax.process_index()``, or 0 when JAX/its backend is unavailable —
    exports must stay usable from import-light host tooling."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def process_count() -> int:
    """``jax.process_count()``, or 1 when JAX/its backend is unavailable."""
    try:
        import jax

        return int(jax.process_count())
    except Exception:
        return 1


# ------------------------------------------------------------------ gathering
def gather_reports(
    local: Optional[Mapping[str, Any]] = None,
    *,
    n_processes: Optional[int] = None,
    allgather: Optional[Callable[[Any], Any]] = None,
    on_failure: str = "raise",
) -> List[Dict[str, Any]]:
    """Every process's report snapshot, ordered by process index.

    The local report is JSON-serialized to a ``uint8`` payload and moved with
    two collectives: one allgather of the payload lengths, one of the
    length-padded payloads — reports differ per process (different labels,
    different cache churn), so shapes must be negotiated first.

    ``n_processes``/``allgather`` are injectable for single-process testing,
    exactly like :func:`parallel.coalesce.coalesced_host_sync`; by default
    they resolve to ``jax.process_count()`` and
    ``multihost_utils.process_allgather``.  With one process no collective
    runs and the local report is returned as the only entry.

    ``on_failure`` is the host-loss policy: ``"raise"`` (default) propagates
    a collective that dies mid-gather (a lost host hangs or faults DCN
    gathers); ``"local"`` degrades instead — the local report is returned as
    the only entry, stamped with a ``degraded_gather`` block naming the
    failure, and a warning fires.  Observability degrades; it never takes
    the evaluation down with it.
    """
    if on_failure not in ("raise", "local"):
        raise ValueError(f'on_failure must be "raise" or "local", got {on_failure!r}')
    local_dict: Dict[str, Any] = dict(local) if local is not None else _local_report()
    n_proc = process_count() if n_processes is None else int(n_processes)
    if n_proc == 1:
        return [local_dict]
    if allgather is None:  # pragma: no cover - exercised on real multi-host
        from jax.experimental import multihost_utils

        allgather = multihost_utils.process_allgather
    import jax.numpy as jnp

    payload = np.frombuffer(
        json.dumps(local_dict, sort_keys=True, default=str).encode("utf-8"), dtype=np.uint8
    )
    try:
        lengths = np.asarray(allgather(jnp.asarray([payload.size], dtype=jnp.int32)))
        lengths = lengths.reshape(n_proc)
        padded = np.zeros(int(lengths.max()), dtype=np.uint8)
        padded[: payload.size] = payload
        rows = np.asarray(allgather(jnp.asarray(padded)))
        return [
            json.loads(bytes(rows[p, : int(lengths[p])]).decode("utf-8"))
            for p in range(n_proc)
        ]
    except Exception as err:  # noqa: BLE001 - classified by the on_failure policy
        if on_failure != "local":
            raise
        rank_zero_warn(
            f"fleet gather failed mid-collective ({err!r}); continuing with the local "
            f"report only — fleet telemetry is degraded to 1/{n_proc} processes"
        )
        degraded = dict(local_dict)
        degraded["degraded_gather"] = {
            "error": repr(err),
            "expected_processes": n_proc,
            "gathered_processes": 1,
        }
        return [degraded]


# ---------------------------------------------------------------- wait digests
def sync_wait_digest(report: Mapping[str, Any]) -> Dict[str, Any]:
    """One process's measured sync-wait summary out of its report.

    Prefers the process-wide ``_process`` row that
    :func:`registry.record_sync_wait` maintains (every measured
    block-until-ready window, regardless of owning metric); falls back to
    summing the per-metric ``sync`` spans for reports predating the digest.
    """
    row = report.get("metrics", {}).get("_process")
    if isinstance(row, Mapping):
        s = row.get("spans", {}).get("sync_wait")
        if s:
            return {
                "count": int(s.get("count", 0)),
                "total_us": float(s.get("total_us", 0.0)),
                "max_us": float(s.get("max_us", 0.0)),
                "source": "sync_wait",
            }
    count, total_us, max_us = 0, 0.0, 0.0
    for row in report.get("metrics", {}).values():
        s = row.get("spans", {}).get("sync")
        if s:
            count += int(s.get("count", 0))
            total_us += float(s.get("total_us", 0.0))
            max_us = max(max_us, float(s.get("max_us", 0.0)))
    return {"count": count, "total_us": total_us, "max_us": max_us, "source": "sync"}


def _axis_skew(per_process: Mapping[int, float]) -> Dict[str, Any]:
    """Max/median/min summary of one per-process scalar, naming the max
    process (ties break toward the lowest index) and the max/median ratio."""
    values = [float(v) for v in per_process.values()]
    peak = max(values)
    med = float(statistics.median(values))
    top = min(idx for idx, v in per_process.items() if float(v) == peak)
    return {
        "per_process": {str(idx): float(per_process[idx]) for idx in sorted(per_process)},
        "max": peak,
        "median": med,
        "min": min(values),
        "max_process": top,
        # median 0 means no signal on the axis at all: report a flat 1.0
        # rather than a JSON-hostile infinity
        "skew_ratio": peak / med if med > 0 else 1.0,
    }


def _merge_cache_stats(parts: List[Mapping[str, Any]]) -> Dict[str, Any]:
    """Sum ``compile_cache`` payloads (flat counters plus the two-level
    ``by_entrypoint``/``miss_causes``/``cold_start`` sub-dicts)."""
    out: Dict[str, Any] = {}
    for part in parts:
        for key, val in part.items():
            if isinstance(val, Mapping):
                slot = out.setdefault(key, {})
                for k2, v2 in val.items():
                    if isinstance(v2, Mapping):
                        inner = slot.setdefault(k2, {})
                        for k3, v3 in v2.items():
                            if isinstance(v3, (int, float)):
                                inner[k3] = inner.get(k3, 0) + v3
                    elif isinstance(v2, (int, float)):
                        slot[k2] = slot.get(k2, 0) + v2
            elif isinstance(val, (int, float)) and not isinstance(val, bool):
                out[key] = out.get(key, 0) + val
    return out


# ----------------------------------------------------------------- fleet view
class FleetView:
    """Per-process report snapshots plus the pod-global merge over them.

    Construct directly from a list of reports (ordered by process index), or
    gather live with :meth:`gather`.  Merge semantics:

    * counters sum exactly — every count on every host is preserved,
    * span histograms merge elementwise (the fixed ``SPAN_BUCKETS_US`` edges
      make per-process histograms addable; EMA merges count-weighted),
    * compile-cache stats sum, including ``by_entrypoint``/``miss_causes``,
    * the untouched per-process reports ride along under ``per_process``.

    ``quarantined`` (process indices) excludes those hosts from every merge
    and skew computation — a replica quarantined out of the *sync* quorum
    must not keep polluting the fleet's merged counters or electing itself
    straggler.  Its raw report still rides along under ``per_process`` for
    the post-mortem, and the merged report carries a ``degraded`` block
    naming the excluded processes.
    """

    def __init__(
        self,
        reports: List[Mapping[str, Any]],
        quarantined: Optional[Sequence[int]] = None,
    ) -> None:
        if not reports:
            raise ValueError("FleetView needs at least one process report")
        self.reports: List[Dict[str, Any]] = [dict(r) for r in reports]
        self.quarantined: Tuple[int, ...] = tuple(sorted({int(q) for q in (quarantined or ())}))
        if not self._active():
            raise ValueError(
                f"quarantining processes {list(self.quarantined)} leaves no active "
                f"process in a {len(self.reports)}-report fleet view"
            )

    @classmethod
    def gather(
        cls,
        *,
        n_processes: Optional[int] = None,
        allgather: Optional[Callable[[Any], Any]] = None,
        on_failure: str = "raise",
        quarantined: Optional[Sequence[int]] = None,
    ) -> "FleetView":
        """Gather every process's live report and build the view."""
        return cls(
            gather_reports(
                n_processes=n_processes, allgather=allgather, on_failure=on_failure
            ),
            quarantined=quarantined,
        )

    @property
    def n_processes(self) -> int:
        return len(self.reports)

    def _index_of(self, position: int) -> int:
        proc = self.reports[position].get("process")
        if isinstance(proc, Mapping) and isinstance(proc.get("index"), int):
            return int(proc["index"])
        return position

    def _active(self) -> List[Tuple[int, Dict[str, Any]]]:
        """(position, report) pairs for processes in the merge quorum."""
        return [
            (pos, r)
            for pos, r in enumerate(self.reports)
            if self._index_of(pos) not in self.quarantined
        ]

    # ------------------------------------------------------------- merging
    def merged_metrics(self) -> Dict[str, Any]:
        """Per-label telemetry rows merged across processes: the same label
        on two hosts is the same logical (SPMD-replicated) metric."""
        active = [r for _, r in self._active()]
        labels: List[str] = []
        for r in active:
            for label in r.get("metrics", {}):
                if label not in labels:
                    labels.append(label)
        out: Dict[str, Any] = {}
        for label in labels:
            rows = [r["metrics"][label] for r in active if label in r.get("metrics", {})]
            merged = aggregate_telemetry(rows)
            merged["label"] = label
            merged["class"] = rows[0].get("class", label)
            # accuracy attestations merge pessimistically: the pod-level bound
            # for a label is the WORST per-process composed bound (a value is
            # only as trustworthy as its least-trustworthy replica), stamped
            # with the process that attested it (aggregate_telemetry drops
            # unknown keys, so the merge is explicit here)
            attested = [
                (pos, row["attestation"])
                for pos, row in enumerate(rows)
                if isinstance(row.get("attestation"), Mapping)
            ]
            if attested:
                worst_pos, worst = max(
                    attested, key=lambda pa: float(pa[1].get("bound", 0.0))
                )
                att = dict(worst)
                att["worst_process"] = worst_pos
                att["processes_attesting"] = len(attested)
                observed = [
                    float(a.get("observed_err"))
                    for _, a in attested
                    if a.get("observed_err") is not None
                ]
                if observed:
                    att["observed_err"] = max(observed)
                merged["attestation"] = att
            out[label] = merged
        return dict(sorted(out.items()))

    # ---------------------------------------------------------------- skew
    def skew(self) -> Dict[str, Any]:
        """Per-replica imbalance: sync-wait, reduce-byte, gather-byte,
        retrace, and live-HBM skew, plus the straggler process (the one that
        spent the most measured wall time blocked in collectives)."""
        waits: Dict[int, float] = {}
        wait_digests: Dict[int, Dict[str, Any]] = {}
        bytes_: Dict[int, float] = {}
        gbytes: Dict[int, float] = {}
        traces: Dict[int, float] = {}
        hbm: Dict[int, float] = {}
        observed: Dict[int, float] = {}
        for pos, r in self._active():
            idx = self._index_of(pos)
            digest = sync_wait_digest(r)
            wait_digests[idx] = digest
            waits[idx] = digest["total_us"]
            bytes_[idx] = float(
                r.get("global", {}).get("counters", {}).get("sync_bytes", 0)
            )
            gbytes[idx] = float(
                r.get("global", {}).get("counters", {}).get("sync_gather_bytes", 0)
            )
            traces[idx] = float(r.get("compile_cache", {}).get("traces", 0))
            mem = r.get("global", {}).get("memory")
            hbm[idx] = float(mem.get("current_bytes", 0)) if isinstance(mem, Mapping) else 0.0
            # worst shadow-audited error this process measured, any metric: a
            # replica whose observed error runs away from the fleet's is
            # drifting (stale twin, divergent state, bad link), not just slow
            observed[idx] = max(
                (
                    float(row["attestation"]["observed_err"])
                    for row in r.get("metrics", {}).values()
                    if isinstance(row.get("attestation"), Mapping)
                    and row["attestation"].get("observed_err") is not None
                ),
                default=0.0,
            )
        wait_axis = _axis_skew(waits)
        straggler = wait_axis["max_process"]
        return {
            "n_processes": self.n_processes,
            "sync_wait_us": wait_axis,
            "sync_bytes": _axis_skew(bytes_),
            "gather_bytes": _axis_skew(gbytes),
            "retraces": _axis_skew(traces),
            "hbm_bytes": _axis_skew(hbm),
            "observed_err": _axis_skew(observed),
            "straggler": {
                "process": straggler,
                "wait_total_us": waits[straggler],
                "wait_count": wait_digests[straggler]["count"],
                "vs_median": wait_axis["skew_ratio"],
                "source": wait_digests[straggler]["source"],
            },
        }

    def straggler(self) -> int:
        """Index of the process with the largest measured sync wait."""
        return int(self.skew()["straggler"]["process"])

    def straggler_bound(self, threshold: float = 2.0) -> bool:
        """True when one process dominates the measured sync wait (its
        wait is ``threshold``x the fleet median or more).  The
        :class:`~torchmetrics_tpu.parallel.autotune.SyncAutotuner` consults
        this before committing: a straggler-bound fleet gains nothing from
        cadence/compression tuning — the straggling host is the lever."""
        return float(self.skew()["straggler"]["vs_median"]) >= float(threshold)

    # -------------------------------------------------------------- report
    def report(self) -> Dict[str, Any]:
        """The pod-global merged report (per-process breakdown retained).

        While any process is quarantined (or the gather itself degraded to
        local-only), the report carries a ``degraded`` block — schema 1.6's
        contract that a partial merge is always *labelled* partial.
        """
        merged = self.merged_metrics()
        out: Dict[str, Any] = {
            "schema": 1,
            "enabled": any(bool(r.get("enabled")) for _, r in self._active()),
            "metrics": merged,
            "global": aggregate_telemetry(merged.values()),
            "compile_cache": _merge_cache_stats(
                [r.get("compile_cache", {}) for _, r in self._active()]
            ),
            "fleet": {"n_processes": self.n_processes, "skew": self.skew()},
            "per_process": {
                str(self._index_of(pos)): dict(r) for pos, r in enumerate(self.reports)
            },
            # index None marks a merged exposition; exporters label it "fleet"
            "process": {"index": None, "count": self.n_processes},
        }
        degraded_gather = next(
            (r["degraded_gather"] for r in self.reports if "degraded_gather" in r), None
        )
        if self.quarantined or degraded_gather is not None:
            out["degraded"] = {
                "quarantined_processes": list(self.quarantined),
                "active_processes": len(self._active()),
                "expected_processes": (
                    int(degraded_gather["expected_processes"])
                    if degraded_gather is not None
                    else self.n_processes
                ),
            }
            if degraded_gather is not None:
                out["degraded"]["gather"] = dict(degraded_gather)
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"FleetView(n_processes={self.n_processes})"


def fleet_report(
    *,
    n_processes: Optional[int] = None,
    allgather: Optional[Callable[[Any], Any]] = None,
    on_failure: str = "raise",
    quarantined: Optional[Sequence[int]] = None,
) -> Dict[str, Any]:
    """The pod-global telemetry report.

    Single-process (the common case, and every CPU test) this IS the local
    :func:`registry.report` — byte-identical, no collective, no extra keys.
    Multi-process it gathers every process's snapshot and returns the
    :class:`FleetView` merge; ``on_failure="local"`` survives a host lost
    mid-gather (degraded local-only report), and ``quarantined`` excludes
    those process indices from the merge (see :class:`FleetView`).
    """
    n_proc = process_count() if n_processes is None else int(n_processes)
    if n_proc == 1:
        return _local_report()
    return FleetView.gather(
        n_processes=n_proc, allgather=allgather, on_failure=on_failure, quarantined=quarantined
    ).report()
