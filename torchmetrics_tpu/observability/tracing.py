"""Flight recorder: a bounded ring-buffer timeline of structured trace events.

PR 3's registry answers *how many* and *how long on average*; this module
answers *when* and *in what order*.  While armed, every instrumented host
boundary appends one :class:`TraceEvent` to a fixed-capacity ring buffer:

* eager ``update`` / ``compute`` / ``forward`` spans (per metric instance),
* sync windows — every coalesced collective boundary, with the planner's
  bucket layout and modelled bytes riding in ``args``,
* compile-cache activity — per-entry cold starts (trace+lower+compile) and
  shape-driven retraces, attributed to their miss cause,
* snapshot / restore / non-finite instants from the resilience layer.

The recorder is **off by default twice over**: events only flow while
telemetry is enabled (``observability.enable()`` / ``TM_TPU_TELEMETRY=1``)
AND the recorder is armed (:func:`start` / ``TM_TPU_FLIGHT_RECORDER=1``).
Disarmed, the only cost at an instrumented site is one ``is None`` check on a
module-level sink — and with telemetry off not even that runs (the registry's
shared null span short-circuits first).  Nothing here ever appears in a
traced graph, so arming the recorder can never change a cache key, add a
compile, or perturb a jaxpr.

The buffer is a ring: memory is O(capacity) regardless of run length, and a
multi-hour job keeps the *most recent* window — exactly what a post-mortem
wants.  Export with :func:`chrome_trace` (Chrome trace-event JSON, loads
directly in Perfetto / ``chrome://tracing``) or per-event JSON lines through
the PR 3 exporter front door (``observability.export(fmt="chrome")`` /
``fmt="trace-jsonl"``).

Example::

    from torchmetrics_tpu import observability as obs

    obs.enable()
    obs.tracing.start(capacity=8192)
    ...  # train / eval
    obs.export(fmt="chrome", path="flight.trace.json")  # open in Perfetto
    obs.tracing.stop()
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "CATEGORIES",
    "DEFAULT_CAPACITY",
    "FlightRecorder",
    "TraceEvent",
    "active",
    "chrome_trace",
    "clear",
    "events",
    "recorder",
    "recording",
    "start",
    "stop",
]

#: event categories the recorder emits (the ``cat`` field); Perfetto's track
#: filter groups on these
CATEGORIES = (
    "eager",
    "sync",
    "compile",
    "resilience",
    "guard",
    "policy",
    "memory",
    "accuracy",
    "warmstart",
    "gather",
)

DEFAULT_CAPACITY = 4096

_LOCK = threading.RLock()


class TraceEvent:
    """One Chrome-trace-event-model record.

    ``ph`` is the trace-event phase: ``"X"`` (complete event: ``ts`` +
    ``dur_us``) for spans, ``"i"`` (instant) for point events.  Timestamps
    are microseconds since the recorder's epoch (monotonic clock), so events
    from one process order totally and Perfetto renders them on one timeline.
    """

    __slots__ = ("name", "cat", "ph", "ts_us", "dur_us", "tid", "args")

    def __init__(
        self,
        name: str,
        cat: str,
        ph: str,
        ts_us: float,
        dur_us: float = 0.0,
        tid: str = "host",
        args: Optional[Mapping[str, Any]] = None,
    ) -> None:
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.args = dict(args) if args else {}

    def as_chrome(self, pid: int) -> Dict[str, Any]:
        """This event in Chrome trace-event JSON form."""
        out: Dict[str, Any] = {
            "name": self.name,
            "cat": self.cat,
            "ph": self.ph,
            "ts": round(self.ts_us, 3),
            "pid": pid,
            "tid": self.tid,
        }
        if self.ph == "X":
            out["dur"] = round(self.dur_us, 3)
        if self.ph == "i":
            out["s"] = "t"  # instant scope: thread
        if self.args:
            out["args"] = dict(self.args)
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "cat": self.cat,
            "ph": self.ph,
            "ts_us": round(self.ts_us, 3),
            "dur_us": round(self.dur_us, 3),
            "tid": self.tid,
            "args": dict(self.args),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"TraceEvent({self.cat}/{self.name} ph={self.ph} ts={self.ts_us:.1f}us dur={self.dur_us:.1f}us)"


class FlightRecorder:
    """Fixed-capacity ring buffer of :class:`TraceEvent` rows.

    Appends are O(1) and evict the oldest event once ``capacity`` is hit —
    the recorder keeps the most recent window of a long run.  ``dropped``
    counts evictions so an export can say how much history scrolled away.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"flight recorder capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: "deque[TraceEvent]" = deque(maxlen=capacity)
        self._dropped = 0
        self._epoch = time.perf_counter()  # tmt: ignore[TMT006] -- recorder epoch; host-side only, never traced

    # ------------------------------------------------------------- recording
    def now_us(self) -> float:
        """Microseconds since this recorder's epoch (monotonic)."""
        return (time.perf_counter() - self._epoch) * 1e6  # tmt: ignore[TMT006] -- span timestamping at the host boundary; never traced

    def add(self, event: TraceEvent) -> None:
        with _LOCK:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(event)

    def span(
        self,
        name: str,
        cat: str,
        t0_us: float,
        dur_us: float,
        tid: str = "host",
        **args: Any,
    ) -> None:
        """Append a complete ("X") event covering ``[t0_us, t0_us+dur_us]``."""
        self.add(TraceEvent(name, cat, "X", t0_us, dur_us, tid=tid, args=args))

    def instant(self, name: str, cat: str, tid: str = "host", **args: Any) -> None:
        """Append an instant ("i") event stamped now."""
        self.add(TraceEvent(name, cat, "i", self.now_us(), tid=tid, args=args))

    # --------------------------------------------------------------- reading
    @property
    def dropped(self) -> int:
        return self._dropped

    def __len__(self) -> int:
        return len(self._ring)

    def events(self) -> List[TraceEvent]:
        """Snapshot of the ring, oldest first."""
        with _LOCK:
            return list(self._ring)

    def clear(self) -> None:
        with _LOCK:
            self._ring.clear()
            self._dropped = 0

    # ---------------------------------------------------------------- export
    def chrome_trace(self, extra_metadata: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
        """The ring as a Chrome trace-event JSON object (Perfetto-loadable).

        Uses the object form (``{"traceEvents": [...], ...}``) so metadata —
        including the export ``schema_version`` — rides along; Perfetto and
        ``chrome://tracing`` both accept it.

        ``pid`` is ``jax.process_index()`` (0 when uninitialized), NOT the OS
        pid: per-host recordings then merge into one Perfetto timeline with
        stable, non-colliding process tracks.  ``process_name``/
        ``thread_name`` metadata events (phase ``"M"``) name those tracks.
        """
        from torchmetrics_tpu.observability.export import SCHEMA_VERSION
        from torchmetrics_tpu.observability.fleet import process_index

        pid = process_index()
        meta: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "producer": "torchmetrics_tpu.observability.tracing",
            "capacity": self.capacity,
            "dropped": self._dropped,
            "process_index": pid,
        }
        if extra_metadata:
            meta.update(extra_metadata)
        events = self.events()
        chrome: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {"name": f"torchmetrics_tpu process {pid}"},
            }
        ]
        seen_tids: List[str] = []
        for e in events:
            if e.tid not in seen_tids:
                seen_tids.append(e.tid)
        for tid in seen_tids:
            chrome.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tid},
                }
            )
        chrome.extend(e.as_chrome(pid) for e in events)
        return {
            "traceEvents": chrome,
            "displayTimeUnit": "ms",
            "otherData": meta,
        }


# ------------------------------------------------------------- module facade
_RECORDER: Optional[FlightRecorder] = None


def recorder() -> Optional[FlightRecorder]:
    """The armed recorder, or ``None`` while disarmed."""
    return _RECORDER


def active() -> bool:
    """True when events are actually flowing: armed AND telemetry enabled."""
    if _RECORDER is None:
        return False
    from torchmetrics_tpu.observability import registry as _registry

    return _registry.enabled()


def start(capacity: int = DEFAULT_CAPACITY) -> FlightRecorder:
    """Arm the flight recorder (idempotent; re-arming with a new capacity
    replaces the ring).

    Events only flow while telemetry is *also* enabled
    (``observability.enable()`` / ``TM_TPU_TELEMETRY=1``) — the recorder
    rides the same gate as every other recording helper, so a normally-dark
    job stays dark even with the recorder armed.
    """
    global _RECORDER
    with _LOCK:
        if _RECORDER is None or _RECORDER.capacity != capacity:
            _RECORDER = FlightRecorder(capacity)
    _wire_sinks(True)
    return _RECORDER


def stop() -> Optional[FlightRecorder]:
    """Disarm the recorder and return it (its ring stays readable/exportable)."""
    global _RECORDER
    _wire_sinks(False)
    with _LOCK:
        rec, _RECORDER = _RECORDER, None
    return rec


def clear() -> None:
    with _LOCK:
        if _RECORDER is not None:
            _RECORDER.clear()


def events() -> List[TraceEvent]:
    """Snapshot of the armed recorder's ring (empty when disarmed)."""
    with _LOCK:
        rec = _RECORDER
    return rec.events() if rec is not None else []


def chrome_trace(extra_metadata: Optional[Mapping[str, Any]] = None) -> Dict[str, Any]:
    """Chrome trace-event JSON of the current ring (empty trace if disarmed)."""
    with _LOCK:
        rec = _RECORDER
    if rec is None:
        rec = FlightRecorder(1)  # empty, but schema-complete
    return rec.chrome_trace(extra_metadata)


class _Recording:
    def __init__(self, capacity: int) -> None:
        self._capacity = capacity
        self._was_armed = False

    def __enter__(self) -> FlightRecorder:
        self._was_armed = _RECORDER is not None
        return start(self._capacity)

    def __exit__(self, *exc: Any) -> bool:
        if not self._was_armed:
            stop()
        return False


def recording(capacity: int = DEFAULT_CAPACITY) -> _Recording:
    """Context manager arming the recorder for a scope::

        with obs.tracing.recording() as rec:
            ...  # train
        open("t.json", "w").write(json.dumps(rec.chrome_trace()))
    """
    return _Recording(capacity)


# ----------------------------------------------------------------- the sinks
# The registry (spans/instants) and the compile cache (cold starts/retraces)
# publish into these callbacks only while the recorder is armed; disarmed,
# the hooks are unregistered and the hot paths are back to one None check.
_INSTANT_COUNTERS = {
    "snapshots": ("snapshot", "resilience"),
    "restores": ("restore", "resilience"),
    "nonfinite_events": ("nonfinite", "guard"),
    "durable_saves": ("durable_save", "resilience"),
    "durable_restores": ("durable_restore", "resilience"),
    "io_retries": ("io_retry", "resilience"),
    "skipbacks": ("skipback", "resilience"),
    "quarantines": ("quarantine", "resilience"),
    "staging_sweeps": ("staging_sweep", "resilience"),
    "warmstart_hits": ("warmstart_hit", "warmstart"),
    "warmstart_stale": ("warmstart_stale", "warmstart"),
    "warmstart_corrupt": ("warmstart_corrupt", "warmstart"),
    "warmstart_exports": ("warmstart_export", "warmstart"),
    "warmstart_quarantines": ("warmstart_quarantine", "warmstart"),
}


def _span_sink(label: str, name: str, dur_s: float) -> None:
    """Registry span hook: called at span exit with the just-measured duration."""
    rec = _RECORDER
    if rec is None:
        return
    cat = "sync" if name.startswith("sync") else "eager"
    end_us = rec.now_us()
    rec.span(f"{label}/{name}", cat, end_us - dur_s * 1e6, dur_s * 1e6, tid=label)


def _count_sink(label: str, counter: str, n: int) -> None:
    """Registry counter hook: resilience/guard counters become instants."""
    rec = _RECORDER
    if rec is None:
        return
    mapped = _INSTANT_COUNTERS.get(counter)
    if mapped is not None:
        name, cat = mapped
        rec.instant(f"{label}/{name}", cat, tid=label, count=n)


def _memory_sink(label: str, current_bytes: int, peak_bytes: int, donated: bool) -> None:
    """Registry state-install hook (armed memory plane): one instant per
    sized install, carrying the watermarks so a trace shows residency steps."""
    rec = _RECORDER
    if rec is None:
        return
    rec.instant(
        f"{label}/state_install",
        "memory",
        tid=label,
        current_bytes=int(current_bytes),
        peak_bytes=int(peak_bytes),
        donated=bool(donated),
    )


def _accuracy_sink(label: str, event: str, payload: Mapping[str, Any]) -> None:
    """Registry accuracy hook (armed accuracy plane): attestations and shadow
    audits become instants, so a trace shows *when* a bound was stamped and
    when an audit breached it."""
    rec = _RECORDER
    if rec is None:
        return
    rec.instant(f"{label}/{event}", "accuracy", tid=label, **payload)


def _gather_sink(label: str, event: str, payload: Mapping[str, Any]) -> None:
    """Registry gather hook (armed gather plane): cat-growth steps, measured
    ragged gathers, and advisor advice become instants, so a trace shows the
    cat state growing and the deferred gather paying for it."""
    rec = _RECORDER
    if rec is None:
        return
    rec.instant(f"{label}/{event}", "gather", tid=label, **payload)


def _compile_sink(record: Any) -> None:
    """Compile-cache timing hook (``core.compile.CompileRecord``)."""
    rec = _RECORDER
    if rec is None:
        return
    dur_us = float(record.cold_start_s) * 1e6
    rec.span(
        f"compile/{record.kind}/{record.label}",
        "compile",
        rec.now_us() - dur_us,
        dur_us,
        tid="compile",
        cause=record.cause,
        kind=record.kind,
        fingerprint=record.fingerprint_hash,
    )


def _wire_sinks(arm: bool) -> None:
    from torchmetrics_tpu.core import compile as _compile
    from torchmetrics_tpu.observability import registry as _registry

    if arm:
        _registry.set_trace_sinks(_span_sink, _count_sink)
        _registry.set_memory_trace_sink(_memory_sink)
        _registry.set_accuracy_trace_sink(_accuracy_sink)
        _registry.set_gather_trace_sink(_gather_sink)
        _compile.add_compile_timing_observer(_compile_sink)
    else:
        _registry.set_trace_sinks(None, None)
        _registry.set_memory_trace_sink(None)
        _registry.set_accuracy_trace_sink(None)
        _registry.set_gather_trace_sink(None)
        _compile.remove_compile_timing_observer(_compile_sink)


def to_json(path: str, extra_metadata: Optional[Mapping[str, Any]] = None) -> str:
    """Write the current ring as a Chrome trace file and return the path."""
    payload = chrome_trace(extra_metadata)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, separators=(",", ":"))
    return path


# honour TM_TPU_FLIGHT_RECORDER=1 at import (telemetry must still be enabled
# for events to flow — the double gate is deliberate)
if os.environ.get("TM_TPU_FLIGHT_RECORDER", "").strip().lower() in ("1", "true", "on", "yes"):
    start(int(os.environ.get("TM_TPU_FLIGHT_RECORDER_CAPACITY", str(DEFAULT_CAPACITY))))
