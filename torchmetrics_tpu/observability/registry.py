"""Per-metric instrumentation registry.

Every instrumented site in the library funnels through this module:
``Metric.update/compute/forward/reset`` count themselves and time their
host-side boundary, ``parallel/sync.py`` and ``parallel/ragged.py`` record
cross-device syncs and the modelled per-chip byte traffic
(``utilities.benchmark.sync_bytes_per_chip``), ``resilience/snapshot.py``
records snapshot/restore events, ``core/guards.py``-driven non-finite
detections land as ``nonfinite_events``, and ``core/compile.py`` pushes
per-entrypoint cache hits/misses/traces through the observer hook
(:func:`enable` subscribes, :func:`disable` unsubscribes).

Design constraints, in order:

* **Disabled is free.**  The module-level flag gates every recording helper
  with one boolean check; no compile-cache observer is registered while
  disabled, spans return a shared null context manager, and nothing here
  ever appears in a traced graph — so toggling telemetry can never change a
  cache key or add a retrace.
* **No unbounded growth.**  Timing spans accumulate into fixed log-spaced
  histogram buckets plus an EMA — O(1) memory per (instance, span) pair no
  matter how many steps run.  Telemetry of garbage-collected metrics folds
  into one ``_retired`` aggregate.
* **No footprint on the metric.**  Telemetry is keyed on ``id(metric)`` in a
  module dict with a ``weakref.finalize`` reaper — storing it as an instance
  attribute would leak into ``deepcopy``/pickle and the config fingerprint.
  (A ``WeakKeyDictionary`` is out: ``Metric.__eq__`` builds a compositional
  metric, so hash-bucket collisions would compare-by-composition.)
"""

from __future__ import annotations

import bisect
import logging
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "COUNTER_NAMES",
    "MetricTelemetry",
    "ObservationWindow",
    "SPAN_BUCKETS_US",
    "accuracy_armed",
    "accuracy_trace",
    "aggregate_telemetry",
    "annotate",
    "attest_compute",
    "count",
    "count_existing",
    "diff_report",
    "disable",
    "enable",
    "enabled",
    "gather_armed",
    "gather_trace",
    "memory_armed",
    "observe",
    "record_attestation",
    "record_cat_growth",
    "record_measured_gather",
    "record_measured_sync",
    "record_quant_error",
    "record_state_install",
    "record_state_snapshot",
    "record_sync",
    "record_sync_wait",
    "report",
    "reset_telemetry",
    "set_accuracy_armed",
    "set_accuracy_attestor",
    "set_accuracy_trace_sink",
    "set_gather_armed",
    "set_gather_trace_sink",
    "set_memory_armed",
    "set_memory_sizer",
    "set_memory_trace_sink",
    "set_trace_sinks",
    "span",
    "telemetry_for",
]

_log = logging.getLogger("torchmetrics_tpu.observability")

_LOCK = threading.RLock()

#: Counter slots every :class:`MetricTelemetry` starts from.  ``sync_bytes``
#: is the modelled per-chip *wire* traffic of the psum family (compressed
#: when a compression config is active), ``sync_bytes_raw`` the same model
#: before compression (the two are equal for exact syncs);
#: ``sync_gather_bytes`` is the gather family's modelled per-chip wire
#: traffic (ragged/cat-state all-gathers are never compressed, so the family
#: has no raw twin); everything else is an event count.
COUNTER_NAMES = (
    "updates",
    "computes",
    "forwards",
    "resets",
    "syncs",
    "sync_bytes",
    "sync_bytes_raw",
    "sync_gather_bytes",
    "collectives",
    "donated_installs",
    "copied_installs",
    "nonfinite_events",
    "snapshots",
    "restores",
    "policy_commits",
    "policy_vetoes",
    "policy_rollbacks",
    "durable_saves",
    "durable_restores",
    "io_retries",
    "skipbacks",
    "quarantines",
    "staging_sweeps",
    "warmstart_hits",
    "warmstart_stale",
    "warmstart_corrupt",
    "warmstart_exports",
    "warmstart_quarantines",
)

#: Upper edges (microseconds) of the fixed span histogram; one overflow
#: bucket (+Inf) rides on the end.  Log-spaced from sub-dispatch latencies to
#: full host syncs.
SPAN_BUCKETS_US = (
    50.0,
    100.0,
    250.0,
    500.0,
    1_000.0,
    2_500.0,
    5_000.0,
    10_000.0,
    50_000.0,
    100_000.0,
    1_000_000.0,
)
_BUCKET_EDGES_S = tuple(us / 1e6 for us in SPAN_BUCKETS_US)

#: Smoothing factor for the per-span exponential moving average.
EMA_ALPHA = 0.1

_ENABLED = os.environ.get("TM_TPU_TELEMETRY", "").strip().lower() in ("1", "true", "on", "yes")

# Flight-recorder sinks (observability/tracing.py).  ``None`` while the
# recorder is disarmed, so the per-event cost of an idle recorder is one
# ``is None`` check *after* the ``_ENABLED`` gate already passed.
_SPAN_SINK: Optional[Callable[[str, str, float], None]] = None
_COUNT_SINK: Optional[Callable[[str, str, int], None]] = None


def set_trace_sinks(
    span_sink: Optional[Callable[[str, str, float], None]],
    count_sink: Optional[Callable[[str, str, int], None]],
) -> None:
    """Install (or clear, with ``None``) the flight-recorder event sinks.

    ``span_sink(label, span_name, seconds)`` fires at every span exit;
    ``count_sink(label, counter_name, n)`` at every counter bump.  Both run
    outside ``_LOCK`` and only while telemetry is enabled."""
    global _SPAN_SINK, _COUNT_SINK
    with _LOCK:
        _SPAN_SINK = span_sink
        _COUNT_SINK = count_sink


# Memory-plane hooks (observability/memory.py).  The sizer turns a state
# pytree into per-leaf resident bytes without touching device buffers; the
# trace sink mirrors installs into the flight recorder's "memory" category.
# ``_MEMORY_ARMED`` is the second half of a double gate: live state-HBM
# accounting records only while telemetry is enabled *and* the memory plane
# is armed, so plain ``enable()`` keeps its existing cost profile.
_MEMORY_ARMED = False
_MEMORY_SIZER: Optional[Callable[[Any], Tuple[Dict[str, Dict[str, int]], int]]] = None
_MEMORY_TRACE_SINK: Optional[Callable[[str, int, int, bool], None]] = None


def set_memory_armed(armed: bool) -> None:
    """Arm (or disarm) live state-HBM accounting.  Prefer the front door,
    :func:`observability.memory.enable_memory_telemetry`, which also arms the
    compile cache's executable-analysis capture."""
    global _MEMORY_ARMED
    with _LOCK:
        _MEMORY_ARMED = bool(armed)


def memory_armed() -> bool:
    return _MEMORY_ARMED


def set_memory_sizer(sizer: Optional[Callable[[Any], Tuple[Dict[str, Dict[str, int]], int]]]) -> None:
    """Install the state-pytree sizer: ``sizer(state) -> (leaves, resident)``
    where ``leaves`` maps leaf name to ``{"bytes", "logical_bytes"}`` and
    ``resident`` is the addressable-shard byte total."""
    global _MEMORY_SIZER
    with _LOCK:
        _MEMORY_SIZER = sizer


def set_memory_trace_sink(sink: Optional[Callable[[str, int, int, bool], None]]) -> None:
    """Install (or clear) the flight-recorder memory sink:
    ``sink(label, current_bytes, peak_bytes, donated)`` fires per install."""
    global _MEMORY_TRACE_SINK
    with _LOCK:
        _MEMORY_TRACE_SINK = sink


# Gather-plane hooks (observability/gathers.py).  ``_GATHER_ARMED`` is the
# second half of the plane's double gate: live cat-state growth attribution
# and measured-gather rows record only while telemetry is enabled *and* the
# gather plane is armed, so plain ``enable()`` keeps its existing cost
# profile.  The trace sink mirrors cat-growth/measured-gather events into
# the flight recorder's "gather" category.
_GATHER_ARMED = False
_GATHER_TRACE_SINK: Optional[Callable[[str, str, Dict[str, Any]], None]] = None


def set_gather_armed(armed: bool) -> None:
    """Arm (or disarm) live cat-state growth attribution.  Prefer the front
    door, :func:`observability.gathers.enable_gather_telemetry`."""
    global _GATHER_ARMED
    with _LOCK:
        _GATHER_ARMED = bool(armed)


def gather_armed() -> bool:
    return _GATHER_ARMED


def set_gather_trace_sink(sink: Optional[Callable[[str, str, Dict[str, Any]], None]]) -> None:
    """Install (or clear) the flight-recorder gather sink:
    ``sink(label, event, payload)`` fires per cat-growth/measured-gather
    event."""
    global _GATHER_TRACE_SINK
    with _LOCK:
        _GATHER_TRACE_SINK = sink


# Accuracy-plane hooks (observability/accuracy.py).  The attestor turns a
# metric instance into a :class:`~torchmetrics_tpu.observability.accuracy.
# ValueAttestation` from registry/policy/sketch state alone; the trace sink
# mirrors attestation events into the flight recorder's "accuracy" category.
# ``_ACCURACY_ARMED`` is the second half of the plane's double gate — value
# attestations compose only while telemetry is enabled *and* the accuracy
# plane is armed, so plain ``enable()`` keeps its existing cost profile.
_ACCURACY_ARMED = False
_ACCURACY_ATTESTOR: Optional[Callable[[Any], None]] = None
_ACCURACY_TRACE_SINK: Optional[Callable[[str, str, Dict[str, Any]], None]] = None


def set_accuracy_armed(armed: bool) -> None:
    """Arm (or disarm) compute-time value attestations.  Prefer the front
    door, :func:`observability.accuracy.enable_accuracy_telemetry`."""
    global _ACCURACY_ARMED
    with _LOCK:
        _ACCURACY_ARMED = bool(armed)


def accuracy_armed() -> bool:
    return _ACCURACY_ARMED


def set_accuracy_attestor(attestor: Optional[Callable[[Any], None]]) -> None:
    """Install the compute-time attestor: ``attestor(metric)`` composes and
    records the metric's :class:`ValueAttestation` (observability/accuracy.py
    owns the composition; the registry only gates the call)."""
    global _ACCURACY_ATTESTOR
    with _LOCK:
        _ACCURACY_ATTESTOR = attestor


def set_accuracy_trace_sink(sink: Optional[Callable[[str, str, Dict[str, Any]], None]]) -> None:
    """Install (or clear) the flight-recorder accuracy sink:
    ``sink(label, event, payload)`` fires per attestation/audit event."""
    global _ACCURACY_TRACE_SINK
    with _LOCK:
        _ACCURACY_TRACE_SINK = sink


class SpanStats:
    """Fixed-size latency accumulator: count/total/max, EMA, and a
    log-bucketed histogram.  O(1) memory regardless of sample count."""

    __slots__ = ("count", "total_s", "max_s", "ema_s", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.ema_s = 0.0
        self.buckets = [0] * (len(_BUCKET_EDGES_S) + 1)

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds
        self.ema_s = seconds if self.count == 1 else (
            EMA_ALPHA * seconds + (1.0 - EMA_ALPHA) * self.ema_s
        )
        self.buckets[bisect.bisect_left(_BUCKET_EDGES_S, seconds)] += 1

    def absorb(self, other: "SpanStats") -> None:
        if other.count == 0:
            return
        self.total_s += other.total_s
        self.max_s = max(self.max_s, other.max_s)
        # EMA has no exact merge; weight by sample count.
        total = self.count + other.count
        self.ema_s = (self.count * self.ema_s + other.count * other.ema_s) / total
        self.count = total
        for i, n in enumerate(other.buckets):
            self.buckets[i] += n

    def as_dict(self) -> Dict[str, Any]:
        mean = self.total_s / self.count if self.count else 0.0
        return {
            "count": self.count,
            "total_us": self.total_s * 1e6,
            "mean_us": mean * 1e6,
            "ema_us": self.ema_s * 1e6,
            "max_us": self.max_s * 1e6,
            "buckets": [
                [edge if i < len(SPAN_BUCKETS_US) else None, self.buckets[i]]
                for i, edge in enumerate(SPAN_BUCKETS_US + (None,))
            ],
        }


class MetricTelemetry:
    """Counters, per-entrypoint cache stats, and timing spans for one metric
    instance (or one synthetic aggregate like ``_retired``)."""

    __slots__ = (
        "label",
        "cls",
        "counters",
        "cache",
        "spans",
        "sync_buckets",
        "memory",
        "gathers",
        "quorum",
        "attestation",
    )

    def __init__(self, label: str, cls: str) -> None:
        self.label = label
        self.cls = cls
        self.counters: Dict[str, int] = {name: 0 for name in COUNTER_NAMES}
        self.cache: Dict[str, Dict[str, int]] = {}
        self.spans: Dict[str, SpanStats] = {}
        #: degraded-mode stamp (schema 1.6 ``quorum`` block): the owning
        #: target's :func:`resilience.quarantine.degradation_report`, set on
        #: quarantine transitions and absent while the full quorum is healthy
        self.quorum: Optional[Dict[str, Any]] = None
        #: per-bucket measured-vs-model sync cost, keyed ``"dtype/op"`` (ring
        #: buckets) or ``"gather/dtype"`` (passthrough leaves); filled by
        #: :func:`record_measured_sync`
        self.sync_buckets: Dict[str, Dict[str, float]] = {}
        #: live state-HBM watermarks, filled by :func:`record_state_install`
        #: while the memory plane is armed (observability/memory.py)
        self.memory: Dict[str, Any] = self._fresh_memory()
        #: per-leaf cat-state growth attribution (schema 1.10 ``gathers``
        #: block), filled by :func:`record_cat_growth` while the gather plane
        #: is armed (observability/gathers.py); exported only once a step has
        #: been recorded so unarmed reports stay byte-identical to 1.9
        self.gathers: Dict[str, Any] = self._fresh_gathers()
        #: latest compute-time value attestation (schema 1.7 ``attestation``
        #: block), stamped by :func:`record_attestation` while the accuracy
        #: plane is armed and the value carries a nonzero bound — exact
        #: computes leave the slot ``None`` so unapproximated reports stay
        #: byte-identical to 1.6 (same contract as ``quorum``)
        self.attestation: Optional[Dict[str, Any]] = None

    @staticmethod
    def _fresh_memory() -> Dict[str, Any]:
        return {
            "current_bytes": 0,
            "peak_bytes": 0,
            "installs": 0,
            "snapshots": 0,
            "donated_install_bytes": 0,
            "copied_install_bytes": 0,
            "leaves": {},
        }

    @staticmethod
    def _fresh_gathers() -> Dict[str, Any]:
        return {
            "steps": 0,
            "cat_elements": 0,
            "cat_bytes": 0,
            "ew_bytes_per_step": 0.0,
            "hwm_bytes": 0,
            "leaves": {},
        }

    @staticmethod
    def _fresh_cat_leaf() -> Dict[str, Any]:
        return {
            "steps": 0,
            "elements": 0,
            "bytes": 0,
            "ew_bytes_per_step": 0.0,
            "hwm_bytes": 0,
        }

    # -- mutation (callers hold _LOCK) -------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def record_cache(self, kind: str, field: str) -> None:
        slot = self.cache.get(kind)
        if slot is None:
            slot = self.cache[kind] = {"hits": 0, "misses": 0, "traces": 0}
        slot[field] = slot.get(field, 0) + 1

    def record_span(self, name: str, seconds: float) -> None:
        stats = self.spans.get(name)
        if stats is None:
            stats = self.spans[name] = SpanStats()
        stats.record(seconds)

    @staticmethod
    def _new_bucket_row() -> Dict[str, Any]:
        return {
            "syncs": 0,
            "elements": 0,
            "measured_us": 0.0,
            "model_naive_bytes": 0,
            "model_ring_bytes": 0,
            "model_raw_bytes": 0,
            "model_dcn_bytes": 0,
            "quant_rel_err_sum": 0.0,
            "quant_err_count": 0,
            "compression": "none",
            "route": "flat",
        }

    def record_bucket(
        self,
        key: str,
        elements: int,
        measured_s: float,
        naive_bytes: int,
        ring_bytes: int,
        raw_bytes: Optional[int] = None,
        compression: str = "none",
    ) -> None:
        row = self.sync_buckets.get(key)
        if row is None:
            row = self.sync_buckets[key] = self._new_bucket_row()
        row["syncs"] += 1
        row["elements"] += int(elements)
        row["measured_us"] += measured_s * 1e6
        row["model_naive_bytes"] += int(naive_bytes)
        row["model_ring_bytes"] += int(ring_bytes)
        # raw = the uncompressed ring model; equals ring for exact buckets
        row["model_raw_bytes"] += int(ring_bytes if raw_bytes is None else raw_bytes)
        row["compression"] = compression

    def record_quant_error(self, key: str, rel_err: float) -> None:
        row = self.sync_buckets.get(key)
        if row is None:
            # a measurement arriving before any recorded sync still lands
            self.record_bucket(key, 0, 0.0, 0, 0)
            row = self.sync_buckets[key]
            row["syncs"] -= 1
        row["quant_rel_err_sum"] = row.get("quant_rel_err_sum", 0.0) + float(rel_err)
        row["quant_err_count"] = row.get("quant_err_count", 0) + 1

    def record_state_memory(
        self,
        leaves: Dict[str, Dict[str, int]],
        resident: int,
        donated: bool,
        count_install: bool = True,
    ) -> None:
        mem = self.memory
        mem["current_bytes"] = int(resident)
        if resident > mem["peak_bytes"]:
            mem["peak_bytes"] = int(resident)
        if count_install:
            mem["installs"] += 1
            mem["donated_install_bytes" if donated else "copied_install_bytes"] += int(resident)
        else:
            mem["snapshots"] += 1
        mem["leaves"] = leaves

    def record_cat_growth(self, rows: Mapping[str, Mapping[str, int]]) -> None:
        """Fold one update step's per-leaf cat-state growth into the
        ``gathers`` block.  ``rows`` maps leaf name to ``{"elements",
        "bytes"}`` deltas appended this step, plus optional ``total_bytes``
        (the leaf's running cat-state size, for the high-watermark)."""
        g = self.gathers
        g["steps"] += 1
        step_bytes = 0
        step_elements = 0
        total_bytes = 0
        for leaf, r in rows.items():
            row = g["leaves"].get(leaf)
            if row is None:
                row = g["leaves"][leaf] = self._fresh_cat_leaf()
            d_e = int(r.get("elements", 0))
            d_b = int(r.get("bytes", 0))
            row["steps"] += 1
            row["elements"] += d_e
            row["bytes"] += d_b
            row["ew_bytes_per_step"] = float(d_b) if row["steps"] == 1 else (
                EMA_ALPHA * d_b + (1.0 - EMA_ALPHA) * row["ew_bytes_per_step"]
            )
            tot = int(r.get("total_bytes", row["bytes"]))
            if tot > row["hwm_bytes"]:
                row["hwm_bytes"] = tot
            step_bytes += d_b
            step_elements += d_e
            total_bytes += tot
        g["cat_elements"] += step_elements
        g["cat_bytes"] += step_bytes
        g["ew_bytes_per_step"] = float(step_bytes) if g["steps"] == 1 else (
            EMA_ALPHA * step_bytes + (1.0 - EMA_ALPHA) * g["ew_bytes_per_step"]
        )
        if total_bytes > g["hwm_bytes"]:
            g["hwm_bytes"] = total_bytes

    def absorb(self, other: "MetricTelemetry") -> None:
        for name, n in other.counters.items():
            self.counters[name] = self.counters.get(name, 0) + n
        for kind, slot in other.cache.items():
            for field, n in slot.items():
                mine = self.cache.setdefault(kind, {"hits": 0, "misses": 0, "traces": 0})
                mine[field] = mine.get(field, 0) + n
        for name, stats in other.spans.items():
            self.spans.setdefault(name, SpanStats()).absorb(stats)
        for key, row in other.sync_buckets.items():
            mine = self.sync_buckets.setdefault(key, self._new_bucket_row())
            for field, n in row.items():
                if isinstance(n, str):
                    mine[field] = n
                else:
                    mine[field] = mine.get(field, 0) + n
        # A retired metric's state is freed, so residency (current/leaves)
        # does not carry over; the cumulative install bytes do, and the peak
        # keeps high-watermark semantics.
        om = other.memory
        mem = self.memory
        mem["peak_bytes"] = max(mem["peak_bytes"], om["peak_bytes"])
        mem["installs"] += om["installs"]
        mem["snapshots"] += om["snapshots"]
        mem["donated_install_bytes"] += om["donated_install_bytes"]
        mem["copied_install_bytes"] += om["copied_install_bytes"]
        # A retired metric's cat state is freed, but its recorded growth and
        # high-watermark keep their cumulative semantics.  Leaf names collide
        # across metrics, so per-leaf rows stay with the original row.
        og = other.gathers
        g = self.gathers
        if og["steps"]:
            total = g["steps"] + og["steps"]
            g["ew_bytes_per_step"] = (
                g["steps"] * g["ew_bytes_per_step"] + og["steps"] * og["ew_bytes_per_step"]
            ) / total
            g["steps"] = total
            g["cat_elements"] += og["cat_elements"]
            g["cat_bytes"] += og["cat_bytes"]
            g["hwm_bytes"] = max(g["hwm_bytes"], og["hwm_bytes"])

    def clear(self) -> None:
        self.counters = {name: 0 for name in COUNTER_NAMES}
        self.cache = {}
        self.spans = {}
        self.sync_buckets = {}
        self.memory = self._fresh_memory()
        self.gathers = self._fresh_gathers()
        self.quorum = None
        self.attestation = None

    @property
    def active(self) -> bool:
        return (
            any(self.counters.values())
            or any(any(slot.values()) for slot in self.cache.values())
            or any(s.count for s in self.spans.values())
            or bool(self.sync_buckets)
            or self.memory["installs"] > 0
            or self.memory["snapshots"] > 0
            or self.gathers["steps"] > 0
        )

    @staticmethod
    def _bucket_row(row: Mapping[str, Any]) -> Dict[str, Any]:
        out = dict(row)
        # measured-vs-model: the granule floor the ring model keeps and the
        # naive 2(n-1)/n model misses — positive when tiny buffers pay a
        # full granule per ring step
        out["residual_bytes"] = int(row.get("model_ring_bytes", 0)) - int(
            row.get("model_naive_bytes", 0)
        )
        return out

    # -- export -------------------------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        with _LOCK:
            out = {
                "label": self.label,
                "class": self.cls,
                "counters": dict(self.counters),
                "cache": {kind: dict(slot) for kind, slot in sorted(self.cache.items())},
                "spans": {name: s.as_dict() for name, s in sorted(self.spans.items())},
                "sync_buckets": {
                    key: self._bucket_row(row)
                    for key, row in sorted(self.sync_buckets.items())
                },
                "memory": {
                    **{k: v for k, v in self.memory.items() if k != "leaves"},
                    "leaves": {
                        name: dict(leaf) for name, leaf in sorted(self.memory["leaves"].items())
                    },
                },
            }
            # only once the gather plane recorded a step: unarmed reports
            # stay byte-identical to 1.9 (same contract as quorum)
            if self.gathers["steps"] > 0:
                out["gathers"] = {
                    **{k: v for k, v in self.gathers.items() if k != "leaves"},
                    "leaves": {
                        name: dict(leaf)
                        for name, leaf in sorted(self.gathers["leaves"].items())
                    },
                }
            # only while degraded: healthy reports stay byte-identical to 1.5
            if self.quorum is not None:
                out["quorum"] = dict(self.quorum)
            # only for approximate values: exact computes stay byte-identical
            # to 1.6 (the attestor records them out-of-band instead)
            if self.attestation is not None:
                out["attestation"] = dict(self.attestation)
            return out

    # ``m.telemetry.snapshot()`` reads nicer than ``as_dict`` at call sites
    snapshot = as_dict

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"MetricTelemetry({self.label!r}, counters={self.counters!r})"


# ------------------------------------------------------------------ storage
_BY_ID: Dict[int, MetricTelemetry] = {}
_CLASS_SEQ: Dict[str, int] = {}
_RETIRED = MetricTelemetry("_retired", "_retired")
_UNATTRIBUTED = MetricTelemetry("_unattributed", "_unattributed")
#: process-wide sync-wait digest: every measured block-until-ready window
#: lands here (span ``sync_wait``) regardless of owning metric, so the fleet
#: plane (observability/fleet.py) can rank processes by how long they sat
#: blocked in collectives.  Spans only — counters stay zero so the row never
#: double-counts events in the global aggregate.
_PROCESS = MetricTelemetry("_process", "_process")


def _retire(oid: int) -> None:
    with _LOCK:
        t = _BY_ID.pop(oid, None)
        if t is not None and t.active:
            _RETIRED.absorb(t)


def telemetry_for(obj: Any, create: bool = True) -> Optional[MetricTelemetry]:
    """The :class:`MetricTelemetry` for ``obj`` (created on first touch).

    Labels are ``<ClassName>#<seq>`` in first-seen order per class.  Entries
    follow the instance's lifetime: a ``weakref.finalize`` reaper folds the
    telemetry of collected instances into the ``_retired`` aggregate.
    """
    if obj is None:
        return _UNATTRIBUTED
    with _LOCK:
        t = _BY_ID.get(id(obj))
        if t is None and create:
            cls = type(obj).__name__
            seq = _CLASS_SEQ.get(cls, 0)
            _CLASS_SEQ[cls] = seq + 1
            t = MetricTelemetry(f"{cls}#{seq}", cls)
            _BY_ID[id(obj)] = t
            try:
                weakref.finalize(obj, _retire, id(obj))
            except TypeError:  # non-weakrefable owner: entry lives until reset
                pass
        return t


# ------------------------------------------------------------ enable/disable
def enabled() -> bool:
    return _ENABLED


def enable() -> None:
    """Turn telemetry on and subscribe to compile-cache events.

    Also reachable at import time via ``TM_TPU_TELEMETRY=1``.
    """
    global _ENABLED
    with _LOCK:
        _ENABLED = True
    from torchmetrics_tpu.core import compile as _compile

    _compile.add_cache_observer(_on_cache_event)
    _compile.add_compile_timing_observer(_on_compile_timing)


def disable() -> None:
    """Turn telemetry off; the recording helpers revert to no-ops."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
    from torchmetrics_tpu.core import compile as _compile

    _compile.remove_cache_observer(_on_cache_event)
    _compile.remove_compile_timing_observer(_on_compile_timing)


def _on_cache_event(event: str, kind: Optional[str], owner: Any) -> None:
    """Compile-cache observer: attribute hits/misses/traces to the owning
    metric instance (or ``_unattributed`` for ownerless entry points)."""
    if not _ENABLED or event not in ("hit", "miss", "trace"):
        return
    field = {"hit": "hits", "miss": "misses", "trace": "traces"}[event]
    with _LOCK:
        telemetry_for(owner).record_cache(kind or "unknown", field)


def _on_compile_timing(record: Any) -> None:
    """Compile-timing observer: fold each measured cold start (trace + lower
    + XLA compile wall time of a cache entry's first dispatch) into the
    owning metric's span stats as ``compile/<kind>``."""
    if not _ENABLED:
        return
    owner = record.owner_ref() if record.owner_ref is not None else None
    with _LOCK:
        telemetry_for(owner).record_span(f"compile/{record.kind or 'unknown'}", record.cold_start_s)


# ------------------------------------------------------------------ recording
def count(obj: Any, name: str, n: int = 1) -> None:
    """Increment counter ``name`` for ``obj`` (no-op while disabled)."""
    if not _ENABLED:
        return
    with _LOCK:
        t = telemetry_for(obj)
        t.inc(name, n)
    if _COUNT_SINK is not None:
        _COUNT_SINK(t.label, name, n)


def record_quorum(obj: Any, quorum: Optional[Mapping[str, Any]]) -> None:
    """Stamp (or clear, with ``None``/non-degraded) the schema-1.6 ``quorum``
    block on ``obj``'s telemetry row.  Called by
    :mod:`torchmetrics_tpu.resilience.quarantine` on every quarantine
    transition so degraded reports/exports always name the surviving quorum."""
    if not _ENABLED:
        return
    with _LOCK:
        t = telemetry_for(obj)
        if quorum is None or not quorum.get("degraded"):
            t.quorum = None
        else:
            t.quorum = dict(quorum)


def count_existing(obj: Any, name: str, n: int = 1) -> None:
    """Like :func:`count` but never *creates* a telemetry entry — used by
    sites that also run on internal throwaway clones (e.g. ``reset`` during
    frozen-clone construction), so transient objects don't pollute the
    registry."""
    if not _ENABLED:
        return
    with _LOCK:
        t = _BY_ID.get(id(obj))
        if t is not None:
            t.inc(name, n)
    if t is not None and _COUNT_SINK is not None:
        _COUNT_SINK(t.label, name, n)


class _NullSpan:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> bool:
        return False


_NULL = _NullSpan()


class _Span:
    """Times a host-side boundary into the owner's :class:`SpanStats` and
    marks it in the profiler timeline (``jax.profiler.TraceAnnotation``)."""

    __slots__ = ("_obj", "_name", "_t0", "_ann")

    def __init__(self, obj: Any, name: str) -> None:
        self._obj = obj
        self._name = name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self) -> "_Span":
        cls = type(self._obj).__name__ if self._obj is not None else "unattributed"
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(f"tm_tpu/{cls}/{self._name}")
            self._ann.__enter__()
        except Exception:  # pragma: no cover - profiler unavailable
            self._ann = None
        self._t0 = time.perf_counter()  # tmt: ignore[TMT006] -- eager telemetry span timing at the host boundary; never traced
        return self

    def __exit__(self, *exc: Any) -> bool:
        dt = time.perf_counter() - self._t0  # tmt: ignore[TMT006] -- eager telemetry span timing at the host boundary; never traced
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:  # pragma: no cover
                pass
        with _LOCK:
            t = telemetry_for(self._obj)
            if t is not None:
                t.record_span(self._name, dt)
        if t is not None and _SPAN_SINK is not None:
            _SPAN_SINK(t.label, self._name, dt)
        return False


def span(obj: Any, name: str):
    """Context manager timing a host boundary for ``obj`` (null when
    disabled)."""
    if not _ENABLED:
        return _NULL
    return _Span(obj, name)


def annotate(name: str):
    """Bare profiler ``TraceAnnotation`` (no timing) — null when disabled."""
    if not _ENABLED:
        return _NULL
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover
        return _NULL


def record_sync(
    obj: Any,
    reductions: Mapping[str, Any],
    state: Mapping[str, Any],
    n_devices: int,
    compression: Any = None,
    shardings: Any = None,
) -> None:
    """Record one cross-device sync for ``obj``: bumps ``syncs``, adds the
    psum family's modelled per-chip traffic to ``sync_bytes`` (compressed
    wire bytes when a
    :class:`~torchmetrics_tpu.parallel.compress.CompressionConfig` is active,
    ``utilities.benchmark.sync_bytes_per_chip`` otherwise), the uncompressed
    psum model to ``sync_bytes_raw``, the gather family's flat all-gather
    model (``(n-1) * local cat bytes``) to ``sync_gather_bytes``, and the
    planner's fused collective count
    (``parallel.coalesce.bucketed_collective_count``) to ``collectives``.
    ``shardings`` prices sharded buckets at the reduce-scatter wire rate
    while ``sync_bytes_raw`` keeps the replicated model, so the two counters
    diff into the sharding savings.  Gather traffic never lands in
    ``sync_bytes``: the two families split so exporters can label them
    ``family="reduce"`` / ``family="gather"``.  Never raises — telemetry
    must not break a sync."""
    if not _ENABLED:
        return
    wire = 0
    raw = 0
    gather_wire = 0
    n_collectives = 0
    try:
        from torchmetrics_tpu.parallel.coalesce import bucketed_collective_count
        from torchmetrics_tpu.utilities.benchmark import (
            split_state_bytes,
            sync_bytes_per_chip,
            sync_wire_bytes_per_chip,
        )

        state = dict(state)
        table = {name: r for name, r in reductions.items() if name in state}
        n = max(int(n_devices), 1)
        _, gather_local = split_state_bytes(table, state)
        gather_wire = (n - 1) * int(gather_local)
        if compression is None and not shardings:
            wire = raw = int(sync_bytes_per_chip(table, state, int(n_devices))) - gather_wire
        else:
            # same plan-based model for both, so wire/raw diff cleanly
            wire = (
                int(
                    sync_wire_bytes_per_chip(
                        table, state, int(n_devices), compression, shardings=shardings
                    )
                )
                - gather_wire
            )
            raw = int(sync_wire_bytes_per_chip(table, state, int(n_devices), None)) - gather_wire
        n_collectives = int(
            bucketed_collective_count(table, state, compression, shardings=shardings)
        )
    except Exception:
        _log.debug("sync byte accounting failed for %r", obj, exc_info=True)
    with _LOCK:
        t = telemetry_for(obj)
        t.inc("syncs")
        t.inc("sync_bytes", wire)
        t.inc("sync_bytes_raw", raw)
        t.inc("sync_gather_bytes", gather_wire)
        t.inc("collectives", n_collectives)


def record_measured_sync(
    obj: Any,
    entries: Iterable[Tuple[Mapping[str, Any], Mapping[str, Any]]],
    n_devices: int,
    seconds: float,
    compression: Any = None,
    shardings: Any = None,
) -> None:
    """Attribute one *measured* coalesced sync (block-until-ready wall time
    at the host boundary) to ``obj``'s per-bucket table.

    ``entries`` is the ``[(reduction table, state), ...]`` list the sync's
    :func:`parallel.coalesce.build_sync_plan` call fused, so the bucket keys
    here match the collectives that actually launched.  Each bucket row gets
    its byte-share of ``seconds`` plus both byte models — the naive
    ``2(n-1)/n`` prediction and the granule-aware ring model — so exporters
    can show the measured-vs-model residual per bucket.  The whole window
    also lands in the owner's span stats as ``sync_measured``.  Never raises.
    """
    if not _ENABLED:
        return
    # (key, elements, naive_b, ring_b, raw_b, mode)
    rows: List[Tuple[str, int, int, int, int, str]] = []
    try:
        import numpy as _np

        from torchmetrics_tpu.parallel.coalesce import bucket_scatter_size, build_sync_plan
        from torchmetrics_tpu.parallel.compress import bucket_wire_bytes
        from torchmetrics_tpu.utilities.benchmark import (
            RING_GRANULE_BYTES,
            ring_reduce_bytes,
            tiled_allgather_bytes,
        )

        entries = [(dict(r), dict(s)) for r, s in entries]
        plan = build_sync_plan(entries, compression=compression, shardings=shardings)
        n = max(int(n_devices), 1)
        for bucket in plan.buckets:
            itemsize = _np.dtype(bucket.dtype).itemsize
            wire_size = bucket_scatter_size(bucket, n)
            payload = wire_size * itemsize
            spec = bucket.compression
            naive_b = int(
                bucket_wire_bytes(wire_size, itemsize, n, spec, None, sharded=bucket.sharded)
            )
            ring_b = int(
                bucket_wire_bytes(
                    wire_size, itemsize, n, spec, RING_GRANULE_BYTES, sharded=bucket.sharded
                )
            )
            raw_b = int(ring_reduce_bytes(payload, n))
            key = f"{bucket.dtype}/{bucket.op}" + ("/sharded" if bucket.sharded else "")
            mode = spec.mode if spec is not None else "none"
            rows.append((key, int(bucket.size), naive_b, ring_b, raw_b, mode))
        for e, name, _reduce in plan.passthrough:
            leaf = entries[e][1][name]
            import jax as _jax

            nbytes = sum(int(v.size) * v.dtype.itemsize for v in _jax.tree.leaves(leaf))
            elems = sum(int(v.size) for v in _jax.tree.leaves(leaf))
            # naive: flat (n-1)*B all-gather; ring: the granule-tiled model
            # (utilities.benchmark.tiled_allgather_bytes), so the exported
            # residual_bytes is the tiling overhead the flat model misses
            naive_b = (n - 1) * nbytes
            ring_b = int(tiled_allgather_bytes(nbytes, n))
            rows.append((f"gather/{name}", elems, naive_b, ring_b, ring_b, "none"))
    except Exception:
        _log.debug("measured sync attribution failed for %r", obj, exc_info=True)
    total_ring = sum(r[3] for r in rows)
    with _LOCK:
        t = telemetry_for(obj)
        t.record_span("sync_measured", seconds)
        for key, elements, naive_b, ring_b, raw_b, mode in rows:
            if total_ring > 0:
                share = seconds * ring_b / total_ring
            else:  # degenerate (1 device / empty buckets): split evenly
                share = seconds / len(rows)
            t.record_bucket(
                key, elements, share, naive_b, ring_b, raw_bytes=raw_b, compression=mode
            )
    if _SPAN_SINK is not None:
        _SPAN_SINK(t.label, "sync_measured", seconds)


def record_sync_wait(seconds: float) -> None:
    """Fold one measured block-until-ready window into the process-wide
    ``_process`` wait digest (span ``sync_wait``).

    Callers are the two measured sync sites (``parallel/sync.py``'s dispatch
    and ``SyncStepper.sync``), right after they attribute the same window
    per-owner through :func:`record_measured_sync` — the digest answers
    "how long did THIS process wait in collectives overall", which is what
    :class:`observability.fleet.FleetView` compares across hosts to name the
    straggler.  No-op while disabled."""
    if not _ENABLED:
        return
    with _LOCK:
        _PROCESS.record_span("sync_wait", float(seconds))


def record_state_install(obj: Any, state: Any, donated: bool) -> None:
    """Record one state install (the pytree rebound to ``metric._state``)
    into the owner's live-HBM watermarks: per-leaf resident bytes
    (addressable shard bytes, not logical bytes — observability/memory.py
    owns the sizer), a current/peak watermark pair, and the donated-vs-copied
    install byte split.

    Double-gated: a no-op unless telemetry is enabled *and* the memory plane
    is armed (:func:`observability.memory.enable_memory_telemetry`).  Reads
    only aval metadata (shape/dtype/sharding), never device buffers, so the
    armed path stays off the trace and adds no retraces.  Never raises."""
    if not _ENABLED or not _MEMORY_ARMED:
        return
    sizer = _MEMORY_SIZER
    if sizer is None:
        return
    try:
        leaves, resident = sizer(state)
    except Exception:
        _log.debug("state memory accounting failed for %r", obj, exc_info=True)
        return
    with _LOCK:
        t = telemetry_for(obj)
        t.record_state_memory(leaves, resident, donated)
        peak = t.memory["peak_bytes"]
    sink = _MEMORY_TRACE_SINK
    if sink is not None:
        sink(t.label, resident, peak, donated)


def record_state_snapshot(obj: Any, state: Any) -> None:
    """Refresh ``obj``'s residency watermarks from ``state`` *on demand*,
    without counting an install — how on-demand reports
    (:func:`observability.memory.snapshot_metric`) attribute bytes of metrics
    whose installs predate arming.  Counted under ``memory["snapshots"]``;
    the donated/copied install byte split is untouched.  Same double gate as
    :func:`record_state_install`.  Never raises."""
    if not _ENABLED or not _MEMORY_ARMED:
        return
    sizer = _MEMORY_SIZER
    if sizer is None:
        return
    try:
        leaves, resident = sizer(state)
    except Exception:
        _log.debug("state memory snapshot failed for %r", obj, exc_info=True)
        return
    with _LOCK:
        telemetry_for(obj).record_state_memory(leaves, resident, donated=False, count_install=False)


def record_cat_growth(obj: Any, rows: Mapping[str, Mapping[str, int]]) -> None:
    """Attribute one update step's cat-state growth to ``obj``: per-leaf
    appended elements/bytes, the EW bytes-per-step growth rate, and the
    cat-state high-watermark (``rows`` maps leaf name to ``{"elements",
    "bytes"[, "total_bytes"]}`` — observability/gathers.py owns the sizing).

    Double-gated like :func:`record_state_install`: a no-op unless telemetry
    is enabled *and* the gather plane is armed
    (:func:`observability.gathers.enable_gather_telemetry`).  Reads only
    host-side sizes the caller already computed — never device buffers or
    traced values — so the armed path stays off the trace and adds no
    retraces.  Never raises."""
    if not _ENABLED or not _GATHER_ARMED:
        return
    try:
        with _LOCK:
            t = telemetry_for(obj)
            t.record_cat_growth(rows)
            g = t.gathers
            payload = {
                "step_bytes": sum(int(r.get("bytes", 0)) for r in rows.values()),
                "cat_bytes": int(g["cat_bytes"]),
                "hwm_bytes": int(g["hwm_bytes"]),
            }
    except Exception:
        _log.debug("cat-state growth accounting failed for %r", obj, exc_info=True)
        return
    sink = _GATHER_TRACE_SINK
    if sink is not None:
        sink(t.label, "cat_growth", payload)


def record_measured_gather(
    obj: Any,
    leaf_sizes: Mapping[str, Tuple[int, int]],
    n_devices: int,
    seconds: float,
    route: str = "flat",
    n_hosts: Optional[int] = None,
    n_local_devices: Optional[int] = None,
) -> None:
    """Attribute one *measured* ragged gather window (block-until-ready wall
    time at the host boundary) to ``obj``'s per-bucket table, the way
    :func:`record_measured_sync` already does for coalesced psum buckets.

    ``leaf_sizes`` maps leaf name to ``(elements, nbytes)`` of the local
    shard the gather shipped.  Each ``gather/<leaf>`` row gets its
    byte-share of ``seconds`` plus both byte models — the flat ``(n-1)*B``
    prediction and the granule-tiled ring model
    (``utilities.benchmark.tiled_allgather_bytes``) — so exporters can show
    the measured-vs-model residual per gather bucket.  The whole window also
    lands in the owner's span stats as ``gather_measured``.  Same double
    gate as :func:`record_cat_growth`.  Never raises.

    ``route`` stamps the lowering the sync committed to.  Under
    ``route="two_stage"`` (``parallel.ragged``'s ICI→DCN lowering;
    ``n_hosts``/``n_local_devices`` describe the topology) the row's wire
    model switches to ``utilities.benchmark.two_stage_gather_bytes``: the
    ring model becomes the two-stage total (ICI + DCN per chip) and the DCN
    share lands in ``model_dcn_bytes`` — cross-host bytes scale with hosts,
    not chips — so the residual against ``measured_us`` prices the route
    actually taken."""
    if not _ENABLED or not _GATHER_ARMED:
        return
    rows: List[Tuple[str, int, int, int, int]] = []
    try:
        from torchmetrics_tpu.utilities.benchmark import (
            tiled_allgather_bytes,
            two_stage_gather_bytes,
        )

        n = max(int(n_devices), 1)
        two_stage = route == "two_stage" and n_hosts is not None and n_local_devices
        for leaf, (elems, nbytes) in leaf_sizes.items():
            naive_b = (n - 1) * int(nbytes)
            if two_stage:
                stages = two_stage_gather_bytes(
                    int(nbytes), max(int(n_hosts), 1), int(n_local_devices)
                )
                ring_b = int(stages["two_stage"]) + int(stages["ici"])
                dcn_b = int(stages["two_stage"])
            else:
                ring_b = int(tiled_allgather_bytes(int(nbytes), n))
                dcn_b = 0
            rows.append((f"gather/{leaf}", int(elems), naive_b, ring_b, dcn_b))
    except Exception:
        _log.debug("measured gather attribution failed for %r", obj, exc_info=True)
    total_ring = sum(r[3] for r in rows)
    with _LOCK:
        t = telemetry_for(obj)
        t.record_span("gather_measured", seconds)
        for key, elements, naive_b, ring_b, dcn_b in rows:
            if total_ring > 0:
                share = seconds * ring_b / total_ring
            else:  # degenerate (1 device / empty leaves): split evenly
                share = seconds / len(rows)
            t.record_bucket(key, elements, share, naive_b, ring_b, raw_bytes=ring_b)
            row = t.sync_buckets[key]
            row["route"] = str(route)
            row["model_dcn_bytes"] = int(row.get("model_dcn_bytes", 0)) + dcn_b
    if _SPAN_SINK is not None:
        _SPAN_SINK(t.label, "gather_measured", seconds)
    sink = _GATHER_TRACE_SINK
    if sink is not None:
        sink(
            t.label,
            "measured",
            {"us": seconds * 1e6, "ring_bytes": total_ring, "leaves": len(rows)},
        )


def gather_trace(label: str, event: str, payload: Mapping[str, Any]) -> None:
    """Mirror one gather-plane event (advice / projection) into the flight
    recorder's "gather" category, when a recorder is armed.  Same double
    gate as :func:`record_cat_growth`."""
    if not _ENABLED or not _GATHER_ARMED:
        return
    sink = _GATHER_TRACE_SINK
    if sink is not None:
        sink(label, event, dict(payload))


def record_quant_error(obj: Any, bucket_key: str, rel_err: float) -> None:
    """Fold one *measured* quantization relative error into ``obj``'s bucket
    row ``bucket_key`` (e.g. ``"float32/sum"``).  Callers measure against an
    exact reference sync (see the bench's compressed leg); telemetry only
    accumulates sum/count so exporters can report the mean.  Never raises."""
    if not _ENABLED:
        return
    with _LOCK:
        t = telemetry_for(obj)
        t.record_quant_error(bucket_key, float(rel_err))


def attest_compute(obj: Any) -> None:
    """Compose and record ``obj``'s value attestation after a ``compute``.

    Double-gated like :func:`record_state_install`: a no-op unless telemetry
    is enabled *and* the accuracy plane is armed
    (:func:`observability.accuracy.enable_accuracy_telemetry`).  The installed
    attestor reads only host-side config/telemetry (sketch geometry, committed
    sync policy, quorum block) — never device buffers or traced values — so
    the armed path stays off the trace and adds no retraces.  Never raises."""
    if not _ENABLED or not _ACCURACY_ARMED:
        return
    attestor = _ACCURACY_ATTESTOR
    if attestor is None:
        return
    try:
        attestor(obj)
    except Exception:
        _log.debug("value attestation failed for %r", obj, exc_info=True)


def accuracy_trace(label: str, event: str, payload: Mapping[str, Any]) -> None:
    """Mirror one accuracy-plane event (attest / audit / audit_breach) into
    the flight recorder's "accuracy" category, when a recorder is armed.
    Same double gate as :func:`record_attestation`."""
    if not _ENABLED or not _ACCURACY_ARMED:
        return
    sink = _ACCURACY_TRACE_SINK
    if sink is not None:
        sink(label, event, dict(payload))


def record_attestation(obj: Any, attestation: Optional[Mapping[str, Any]]) -> None:
    """Stamp (or clear, with ``None``/exact) the schema-1.7 ``attestation``
    block on ``obj``'s telemetry row and mirror the event into the flight
    recorder's "accuracy" category.  Exact (zero-bound) attestations clear
    the slot so unapproximated reports stay byte-identical to schema 1.6."""
    if not _ENABLED or not _ACCURACY_ARMED:
        return
    with _LOCK:
        t = telemetry_for(obj)
        if attestation is None or attestation.get("exact", False):
            t.attestation = None
        else:
            t.attestation = dict(attestation)
    sink = _ACCURACY_TRACE_SINK
    if sink is not None and attestation is not None:
        sink(
            t.label,
            "attest",
            {
                "exact": bool(attestation.get("exact", False)),
                "bound": float(attestation.get("bound", 0.0)),
                "within_budget": attestation.get("within_budget"),
            },
        )


# ------------------------------------------------------------------ reporting
def aggregate_telemetry(parts: Iterable[Mapping[str, Any]]) -> Dict[str, Any]:
    """Sum a list of ``MetricTelemetry.as_dict()`` payloads into one."""
    agg = MetricTelemetry("_aggregate", "_aggregate")
    for part in parts:
        for name, n in part.get("counters", {}).items():
            agg.counters[name] = agg.counters.get(name, 0) + int(n)
        for kind, slot in part.get("cache", {}).items():
            mine = agg.cache.setdefault(kind, {"hits": 0, "misses": 0, "traces": 0})
            for field, n in slot.items():
                mine[field] = mine.get(field, 0) + int(n)
        for name, s in part.get("spans", {}).items():
            stats = agg.spans.setdefault(name, SpanStats())
            merged = SpanStats()
            merged.count = int(s["count"])
            merged.total_s = float(s["total_us"]) / 1e6
            merged.max_s = float(s["max_us"]) / 1e6
            merged.ema_s = float(s["ema_us"]) / 1e6
            merged.buckets = [int(n) for _, n in s["buckets"]]
            stats.absorb(merged)
        for key, row in part.get("sync_buckets", {}).items():
            mine = agg.sync_buckets.setdefault(key, MetricTelemetry._new_bucket_row())
            for field, n in row.items():
                if field == "residual_bytes":  # derived in _bucket_row; recomputed on export
                    continue
                if isinstance(n, str):
                    mine[field] = n
                else:
                    mine[field] = mine.get(field, 0) + n
        # Live aggregation (unlike retirement-time absorb) sums residency:
        # the aggregate's current is total resident state across members, its
        # peak the sum of member peaks — an upper bound on the simultaneous
        # peak.  Leaf names collide across metrics, so leaves stay empty.
        mem = part.get("memory")
        if mem:
            am = agg.memory
            for field in (
                "current_bytes",
                "peak_bytes",
                "installs",
                "snapshots",
                "donated_install_bytes",
                "copied_install_bytes",
            ):
                am[field] += int(mem.get(field, 0))
        # Gather blocks merge the same way: cumulative fields sum, the
        # high-watermark keeps max semantics, the EW rate merges weighted by
        # step count, and colliding leaf names keep leaves out of aggregates.
        gb = part.get("gathers")
        if gb:
            ag = agg.gathers
            steps = int(gb.get("steps", 0))
            total = ag["steps"] + steps
            if total:
                ag["ew_bytes_per_step"] = (
                    ag["steps"] * ag["ew_bytes_per_step"]
                    + steps * float(gb.get("ew_bytes_per_step", 0.0))
                ) / total
            ag["steps"] = total
            ag["cat_elements"] += int(gb.get("cat_elements", 0))
            ag["cat_bytes"] += int(gb.get("cat_bytes", 0))
            ag["hwm_bytes"] = max(ag["hwm_bytes"], int(gb.get("hwm_bytes", 0)))
    return agg.as_dict()


def report() -> Dict[str, Any]:
    """One structured snapshot of everything the registry knows.

    Layout::

        {"schema": 1, "enabled": bool,
         "process": {"index": int, "count": int},    # which host produced it
         "metrics": {label: telemetry-dict, ...},   # live + synthetic rows
         "global": telemetry-dict,                   # sum over all rows
         "compile_cache": cache_stats()}             # incl. by_entrypoint

    Synthetic rows (``_retired``, ``_unattributed``, the ``_process`` wait
    digest) appear only once active.  ``process`` self-describes the report
    for fleet merges (observability/fleet.py) and process-labelled exports.
    """
    from torchmetrics_tpu.observability.fleet import process_count, process_index

    with _LOCK:
        rows = {t.label: t.as_dict() for t in _BY_ID.values()}
        for synth in (_RETIRED, _UNATTRIBUTED, _PROCESS):
            if synth.active:
                rows[synth.label] = synth.as_dict()
    out: Dict[str, Any] = {
        "schema": 1,
        "enabled": _ENABLED,
        "process": {"index": process_index(), "count": process_count()},
        "metrics": dict(sorted(rows.items())),
        "global": aggregate_telemetry(rows.values()),
    }
    try:
        from torchmetrics_tpu.core.compile import cache_stats

        out["compile_cache"] = cache_stats()
    except Exception:  # pragma: no cover
        out["compile_cache"] = {}
    return out


def _diff_num(a: Any, b: Any) -> Any:
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a - b
    return a


def _diff_span(after: Mapping[str, Any], before: Optional[Mapping[str, Any]]) -> Dict[str, Any]:
    if before is None:
        return dict(after)
    count_d = int(after["count"]) - int(before["count"])
    total_d = float(after["total_us"]) - float(before["total_us"])
    prev = [int(n) for _, n in before["buckets"]]
    return {
        "count": count_d,
        "total_us": total_d,
        "mean_us": total_d / count_d if count_d else 0.0,
        # point-in-time stats: the window's EMA/max are the final values
        "ema_us": after["ema_us"],
        "max_us": after["max_us"],
        "buckets": [
            [edge, int(n) - (prev[i] if i < len(prev) else 0)]
            for i, (edge, n) in enumerate(after["buckets"])
        ],
    }


def _diff_tdict(after: Mapping[str, Any], before: Optional[Mapping[str, Any]]) -> Dict[str, Any]:
    if before is None:
        return dict(after)
    out: Dict[str, Any] = {
        "label": after.get("label"),
        "class": after.get("class"),
        "counters": {
            name: int(n) - int(before.get("counters", {}).get(name, 0))
            for name, n in after.get("counters", {}).items()
        },
        "cache": {},
        "spans": {},
        "sync_buckets": {},
    }
    for kind, slot in after.get("cache", {}).items():
        prev = before.get("cache", {}).get(kind, {})
        out["cache"][kind] = {f: int(n) - int(prev.get(f, 0)) for f, n in slot.items()}
    for name, s in after.get("spans", {}).items():
        out["spans"][name] = _diff_span(s, before.get("spans", {}).get(name))
    for key, row in after.get("sync_buckets", {}).items():
        prev = before.get("sync_buckets", {}).get(key, {})
        out["sync_buckets"][key] = {f: _diff_num(n, prev.get(f, 0)) for f, n in row.items()}
    mem = after.get("memory")
    if mem is not None:
        prev_mem = before.get("memory", {})
        out["memory"] = {
            # cumulative fields diff; watermarks and leaves are point-in-time
            # so the window keeps their end-of-window values
            **{k: v for k, v in mem.items() if k != "leaves"},
            "installs": int(mem.get("installs", 0)) - int(prev_mem.get("installs", 0)),
            "snapshots": int(mem.get("snapshots", 0)) - int(prev_mem.get("snapshots", 0)),
            "donated_install_bytes": int(mem.get("donated_install_bytes", 0))
            - int(prev_mem.get("donated_install_bytes", 0)),
            "copied_install_bytes": int(mem.get("copied_install_bytes", 0))
            - int(prev_mem.get("copied_install_bytes", 0)),
            "leaves": dict(mem.get("leaves", {})),
        }
    gb = after.get("gathers")
    if gb is not None:
        prev_gb = before.get("gathers", {})
        out["gathers"] = {
            # cumulative fields diff; the EW rate and high-watermark are
            # point-in-time so the window keeps their end-of-window values
            **{k: v for k, v in gb.items() if k != "leaves"},
            "steps": int(gb.get("steps", 0)) - int(prev_gb.get("steps", 0)),
            "cat_elements": int(gb.get("cat_elements", 0)) - int(prev_gb.get("cat_elements", 0)),
            "cat_bytes": int(gb.get("cat_bytes", 0)) - int(prev_gb.get("cat_bytes", 0)),
            "leaves": dict(gb.get("leaves", {})),
        }
    return out


def _diff_cache_stats(after: Mapping[str, Any], before: Mapping[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in after.items():
        if k == "by_entrypoint":
            out[k] = {
                kind: {
                    f: int(n) - int(before.get(k, {}).get(kind, {}).get(f, 0))
                    for f, n in slot.items()
                }
                for kind, slot in v.items()
            }
        elif isinstance(v, Mapping):  # flat numeric sub-dicts: miss_causes, cold_start
            prev = before.get(k, {})
            out[k] = {f: _diff_num(n, prev.get(f, 0)) for f, n in v.items()}
        else:
            out[k] = _diff_num(v, before.get(k, 0))
    return out


def diff_report(before: Mapping[str, Any], after: Mapping[str, Any]) -> Dict[str, Any]:
    """``after - before`` over two :func:`report` snapshots (counter deltas;
    EMA/max spans keep their end-of-window values)."""
    metrics = {
        label: _diff_tdict(td, before.get("metrics", {}).get(label))
        for label, td in after.get("metrics", {}).items()
    }
    return {
        "schema": after.get("schema", 1),
        "enabled": after.get("enabled", False),
        "process": after.get("process"),
        "metrics": metrics,
        "global": _diff_tdict(after.get("global", {}), before.get("global")),
        "compile_cache": _diff_cache_stats(
            after.get("compile_cache", {}), before.get("compile_cache", {})
        ),
    }


def reset_telemetry() -> None:
    """Zero every live entry and the retired/unattributed aggregates (labels
    and instance identity are kept)."""
    with _LOCK:
        for t in _BY_ID.values():
            t.clear()
        _RETIRED.clear()
        _UNATTRIBUTED.clear()
        _PROCESS.clear()


# ------------------------------------------------------------------- observe
class ObservationWindow:
    """Handle yielded by :func:`observe`: ``before``/``after`` snapshots and,
    once the block exits, their ``diff``."""

    __slots__ = ("label", "before", "after", "diff")

    def __init__(self, label: Optional[str]) -> None:
        self.label = label
        self.before: Dict[str, Any] = {}
        self.after: Dict[str, Any] = {}
        self.diff: Dict[str, Any] = {}

    def export(self, fmt: str = "log", **kwargs: Any) -> Any:
        """Export the window's diff through :func:`observability.export.export`."""
        from torchmetrics_tpu.observability.export import export as _export

        payload = dict(self.diff)
        if self.label is not None:
            payload["window"] = self.label
        return _export(payload, fmt=fmt, **kwargs)


class _Observe:
    def __init__(self, label: Optional[str], turn_on: bool) -> None:
        self._label = label
        self._turn_on = turn_on
        self._prev: Optional[bool] = None
        self.window = ObservationWindow(label)

    def __enter__(self) -> ObservationWindow:
        self._prev = enabled()
        if self._turn_on and not self._prev:
            enable()
        self.window.before = report()
        return self.window

    def __exit__(self, *exc: Any) -> bool:
        self.window.after = report()
        self.window.diff = diff_report(self.window.before, self.window.after)
        if self._turn_on and self._prev is False:
            disable()
        return False


def observe(label: Optional[str] = None, enable: bool = True) -> _Observe:
    """Context manager scoping a telemetry window around a training phase::

        with observe("eval-epoch-3") as window:
            ...  # train/eval steps
        window.diff  # what happened inside the block, as a report delta

    ``enable=True`` (default) turns telemetry on for the window and restores
    the previous flag on exit, so a normally-dark job can observe one phase.
    """
    return _Observe(label, enable)
