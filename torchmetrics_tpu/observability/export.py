"""Pluggable exporters for :func:`observability.registry.report` payloads.

Three backends behind one front door (:func:`export`):

* ``"log"`` — structured lines through the ``torchmetrics_tpu.observability``
  logger (a child of the library logger, which carries a ``NullHandler`` —
  silent until the application configures logging).
* ``"jsonl"`` — one compact JSON object per export appended to a file or
  stream; parse each line back with ``json.loads``.
* ``"prometheus"`` — text exposition format (``# HELP``/``# TYPE``, counter
  ``_total`` samples, cumulative histogram ``_bucket{le=...}`` series) ready
  for a node-exporter textfile collector or an HTTP scrape handler.
* ``"chrome"`` — the flight recorder's ring (``observability/tracing.py``) as
  Chrome trace-event JSON, loadable in Perfetto / ``chrome://tracing``.

Exporters are plain classes with an ``export(report) -> Any`` method; anything
with that shape can be passed to :func:`export` via ``exporter=``.

Machine-readable outputs (JSONL lines and the Chrome trace's ``otherData``)
carry a ``schema_version`` (semver).  Consumers should parse JSONL through
:func:`parse_export_line`, which rejects lines whose *major* version it does
not understand — the forward-compat contract is: minor/patch bumps are
additive, a major bump may break you.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, IO, List, Mapping, Optional

from torchmetrics_tpu.observability.registry import COUNTER_NAMES

__all__ = [
    "ChromeTraceExporter",
    "Exporter",
    "JSONLinesExporter",
    "LoggingExporter",
    "PrometheusExporter",
    "SCHEMA_VERSION",
    "TraceJSONLinesExporter",
    "export",
    "parse_export_line",
    "parse_stats",
    "reset_parse_stats",
]

#: Semver of the machine-readable export payloads (JSONL lines, Chrome-trace
#: ``otherData``).  Major 1 = the PR 3 report layout; 1.1 added the
#: ``schema_version`` field itself and the flight-recorder trace export; 1.2
#: added compressed-collective accounting (``sync_bytes_raw``, per-bucket
#: ``model_raw_bytes`` / quantization-error fields / ``compression`` mode);
#: 1.3 added the fleet telemetry plane — process identity on every payload
#: (``process`` on JSONL lines and report dicts, a ``process`` label on every
#: Prometheus family, ``pid = jax.process_index()`` plus
#: ``process_name``/``thread_name`` metadata events in Chrome traces), the
#: merged fleet report (``fleet``/``per_process`` blocks), and health-monitor
#: payloads (``health`` block, ``health_alert`` JSONL lines); 1.4 added the
#: closed-loop autotuner — ``autotune_decision`` JSONL ledger lines,
#: ``sync_advice`` recommendation lines, the ``autotune`` report block with
#: its ``tm_tpu_autotune_*`` Prometheus families, and the ``policy``
#: flight-recorder category; 1.5 added the memory & cost observability plane
#: — a ``memory`` block on every metric row (live state-HBM watermarks,
#: per-leaf resident bytes, donated-vs-copied install bytes), ``kind:
#: "memory_report"`` payloads (executable memory/cost analyses plus the
#: ShardingAdvisor's replication-waste advisory), the ``tm_tpu_memory_*`` /
#: ``tm_tpu_cost_*`` Prometheus families, an ``entry_bytes`` gauge in
#: ``compile_cache.by_entrypoint``, and the ``memory`` flight-recorder
#: category; 1.6 added the durability & degraded-mode plane — the
#: ``durable_saves`` / ``durable_restores`` / ``io_retries`` / ``skipbacks``
#: / ``quarantines`` counters (and their ``tm_tpu_*_total`` Prometheus
#: families), an optional ``degraded`` block on fleet reports naming the
#: quarantined processes excluded from the merge, and a ``quorum`` block on
#: reports produced while replica quarantine is active; 1.7 added the
#: accuracy attestation plane — an optional ``attestation`` block on metric
#: rows (composed error bound + provenance chain + budget ledger, approximate
#: values only), ``kind: "attestation"`` payloads from
#: ``observability/accuracy.py``, the ``tm_tpu_accuracy_*`` Prometheus
#: families, and the ``accuracy`` flight-recorder category; 1.8 added the
#: cross-replica sharded-state plane — the ShardingAdvisor promoted to an
#: actuator: ``kind: "sharding_advice"`` recommendation payloads exported
#: standalone through the front door (previously only nested inside
#: ``memory_report``), ``kind: "sharding_decision"`` JSONL ledger lines
#: (``autotune_decision``-shaped rows for propose/arm/commit/veto/rollback/
#: audit of per-leaf ``state_sharding`` specs), a ``/sharded`` suffix on
#: measured per-bucket sync row keys, and sharding specs carried in
#: attestation provenance; 1.9 added the executable warm-start plane — the
#: ``warmstart_hits`` / ``warmstart_stale`` / ``warmstart_corrupt`` /
#: ``warmstart_exports`` / ``warmstart_quarantines`` / ``staging_sweeps``
#: counters (and their ``tm_tpu_*_total`` Prometheus families), ``kind:
#: "warmstart_report"`` payloads from ``core/warmstart.py`` (store root,
#: compatibility environment, per-entry ready/stale/quarantined states),
#: three ``miss_causes`` attributions (``warmstart-hit`` /
#: ``warmstart-stale`` / ``warmstart-corrupt``) in ``compile_cache`` blocks,
#: and the ``warmstart`` flight-recorder category; 1.10 added the
#: gather-plane observability — the ``sync_gather_bytes`` counter splitting
#: gather-family traffic out of ``sync_bytes`` (the sync-byte Prometheus
#: families gained a ``family="reduce"|"gather"`` label), an optional
#: ``gathers`` block on metric rows (per-leaf cat-state growth: elements and
#: bytes per step, EW growth rate, high-watermark), ``gather/<leaf>``
#: measured per-bucket rows with flat-vs-tiled byte models, ``kind:
#: "gather_report"`` payloads from ``observability/gathers.py`` (live
#: attribution, 8/16/64-chip projections, GatherAdvisor advice), ``kind:
#: "gather_advice"`` JSONL ledger lines, the ``tm_tpu_gather_*`` Prometheus
#: families, and the ``gather`` flight-recorder category; 1.11 added the
#: gather-plane *actuation* layer — ``kind: "gather_decision"`` ledger lines
#: (GatherAdvisor propose/arm/commit/veto/rollback/audit transitions,
#: interleaved seq-ordered with its ``gather_advice`` lines), a ``commits``
#: block on advice payloads carrying measured post-commit byte cuts,
#: committed-cut advice lines (``"<label>: <action> committed — measured
#: cut <N> B/step"``), ``route``/``model_dcn_bytes`` fields on
#: ``gather/<leaf>`` sync-bucket rows (two-stage ICI→DCN lowering), and the
#: ``gather_approx`` attestation provenance source (sketch-mAP histogram
#: and reservoir corpus-sample error bounds).
SCHEMA_VERSION = "1.11.0"
SCHEMA_MAJOR = int(SCHEMA_VERSION.split(".", 1)[0])


#: running tallies of :func:`parse_export_line` outcomes — the pre-1.1
#: leniency is no longer silent: a consumer can audit how much of its input
#: rode the legacy path (and the first legacy line logs once at DEBUG)
_PARSE_STATS = {"parsed": 0, "legacy_unversioned": 0, "rejected": 0}
_LEGACY_LOGGED = False


def parse_stats() -> Dict[str, int]:
    """Counters of :func:`parse_export_line` outcomes since import (or the
    last :func:`reset_parse_stats`): ``parsed`` lines accepted with a
    version, ``legacy_unversioned`` lines accepted through the pre-1.1
    leniency, ``rejected`` lines that raised."""
    return dict(_PARSE_STATS)


def reset_parse_stats() -> None:
    """Zero the :func:`parse_stats` counters (and re-arm the one-time
    legacy-line debug log)."""
    global _LEGACY_LOGGED
    for key in _PARSE_STATS:
        _PARSE_STATS[key] = 0
    _LEGACY_LOGGED = False


def parse_export_line(line: str) -> Dict[str, Any]:
    """Parse one :class:`JSONLinesExporter` line back into a dict, enforcing
    the schema-version contract.

    Lines without a ``schema_version`` (pre-1.1 exports) are accepted as
    legacy major 1 — counted in :func:`parse_stats` and logged once at DEBUG
    so the leniency is auditable rather than silent.  A
    present-but-unparseable version, or a major version other than
    ``SCHEMA_MAJOR``, raises ``ValueError`` — a consumer must not silently
    misread a payload whose layout it cannot know.
    """
    global _LEGACY_LOGGED
    try:
        payload = json.loads(line)
        if not isinstance(payload, dict):
            raise ValueError(
                f"telemetry export line is not a JSON object: {type(payload).__name__}"
            )
    except ValueError:
        _PARSE_STATS["rejected"] += 1
        raise
    version = payload.get("schema_version")
    if version is None:
        # legacy pre-1.1 line: implied major 1
        _PARSE_STATS["legacy_unversioned"] += 1
        if not _LEGACY_LOGGED:
            _LEGACY_LOGGED = True
            from torchmetrics_tpu.utilities.prints import rank_zero_debug

            rank_zero_debug(
                "parse_export_line: accepted a line without schema_version (legacy "
                "pre-1.1 export); further legacy lines are counted in parse_stats() "
                "without logging"
            )
        return payload
    try:
        major = int(str(version).split(".", 1)[0])
    except ValueError:
        _PARSE_STATS["rejected"] += 1
        raise ValueError(f"unparseable telemetry schema_version {version!r}") from None
    if major != SCHEMA_MAJOR:
        _PARSE_STATS["rejected"] += 1
        raise ValueError(
            f"unsupported telemetry schema_version {version!r}: this reader understands "
            f"major {SCHEMA_MAJOR} only"
        )
    _PARSE_STATS["parsed"] += 1
    return payload

_log = logging.getLogger("torchmetrics_tpu.observability")


def _local_process() -> Dict[str, int]:
    """This process's identity, stamped on payloads that lack one."""
    from torchmetrics_tpu.observability.fleet import process_count, process_index

    return {"index": process_index(), "count": process_count()}


def _process_label(report: Mapping[str, Any]) -> str:
    """The ``process`` label value for a report: its own ``process.index``
    when the payload self-describes (``None`` marks a fleet merge), else the
    local process index."""
    proc = report.get("process") if isinstance(report, Mapping) else None
    if isinstance(proc, Mapping):
        idx = proc.get("index")
        return "fleet" if idx is None else str(idx)
    if isinstance(proc, int):
        return str(proc)
    return str(_local_process()["index"])

#: one-line docs for the Prometheus ``# HELP`` strings
_COUNTER_HELP = {
    "updates": "Metric.update() calls.",
    "computes": "Metric.compute() calls.",
    "forwards": "Metric.forward() calls.",
    "resets": "Metric.reset() calls.",
    "syncs": "Cross-device/host state synchronisations.",
    "sync_bytes": "Modelled per-chip sync wire traffic in bytes (compressed when active).",
    "sync_bytes_raw": "Modelled per-chip sync traffic in bytes before compression.",
    "sync_gather_bytes": "Modelled per-chip gather-family sync traffic in bytes (cat/ragged all-gathers, never compressed).",
    "collectives": "Fused (bucketed) collective launches.",
    "donated_installs": "Compiled state installs with buffer donation.",
    "copied_installs": "Compiled state installs without donation (aliased state).",
    "nonfinite_events": "Non-finite update batches observed by nan_strategy guards.",
    "snapshots": "Resilience snapshots taken.",
    "restores": "State restores (resilience restore / load_state_*).",
    "policy_commits": "SyncAutotuner policy commits applied to this metric's sync path.",
    "policy_vetoes": "SyncAutotuner pending commits vetoed by a guardrail.",
    "policy_rollbacks": "SyncAutotuner committed policies rolled back.",
    "durable_saves": "Durable snapshot generations committed to a backend.",
    "durable_restores": "Restores served from a durable snapshot generation.",
    "io_retries": "Transient checkpoint I/O failures retried by a RetryPolicy.",
    "skipbacks": "Durable restores that skipped a corrupt generation back to an older one.",
    "quarantines": "Replicas quarantined out of the sync quorum.",
    "staging_sweeps": "Orphaned durable .staging- dirs removed by a gc sweep.",
    "warmstart_hits": "Compile-cache misses served by a warm-started durable executable.",
    "warmstart_stale": "Warm-start entries refused for envelope skew (version/flags/mesh).",
    "warmstart_corrupt": "Warm-start entries refused as damaged (CRC/deserialize/dispatch).",
    "warmstart_exports": "Freshly compiled executables published to the durable store.",
    "warmstart_quarantines": "Warm-start entries quarantined (never re-read this process).",
}

#: sync-byte counters carry a collective-family label so reduce (psum) and
#: gather traffic separate cleanly on one dashboard
_COUNTER_FAMILY = {
    "sync_bytes": "reduce",
    "sync_bytes_raw": "reduce",
    "sync_gather_bytes": "gather",
}


class Exporter:
    """Interface: subclasses implement :meth:`export`."""

    def export(self, report: Mapping[str, Any]) -> Any:  # pragma: no cover - interface
        raise NotImplementedError


class LoggingExporter(Exporter):
    """Emit a report as structured log records.

    One summary record for the global aggregate plus one record per metric
    row, each carrying the payload both formatted and as ``extra={"telemetry":
    ...}`` for structured handlers.
    """

    def __init__(self, logger: Optional[logging.Logger] = None, level: int = logging.INFO):
        self.logger = logger if logger is not None else _log
        self.level = level

    def export(self, report: Mapping[str, Any]) -> None:
        glob = report.get("global", {})
        counters = glob.get("counters", {})
        head = ", ".join(f"{k}={v}" for k, v in sorted(counters.items()) if v)
        self.logger.log(
            self.level,
            "telemetry: %s",
            head or "(no activity)",
            extra={"telemetry": dict(report)},
        )
        for label, row in sorted(report.get("metrics", {}).items()):
            row_counters = {k: v for k, v in row.get("counters", {}).items() if v}
            self.logger.log(
                self.level,
                "telemetry[%s]: %s",
                label,
                ", ".join(f"{k}={v}" for k, v in sorted(row_counters.items())) or "(idle)",
                extra={"telemetry_metric": dict(row)},
            )


class JSONLinesExporter(Exporter):
    """Append each report as one JSON line to ``path`` (or a writable
    ``stream``).  ``json.loads`` on any line round-trips the report."""

    def __init__(self, path: Optional[str] = None, stream: Optional[IO[str]] = None):
        if (path is None) == (stream is None):
            raise ValueError("JSONLinesExporter needs exactly one of `path` or `stream`")
        self.path = path
        self.stream = stream

    def export(self, report: Mapping[str, Any]) -> str:
        payload = dict(report)
        payload.setdefault("schema_version", SCHEMA_VERSION)
        # every line names its producing process so multi-host logs merge
        payload.setdefault("process", _local_process())
        line = json.dumps(payload, sort_keys=True, separators=(",", ":"), default=str)
        if self.stream is not None:
            self.stream.write(line + "\n")
            try:
                self.stream.flush()
            except Exception:  # pragma: no cover
                pass
        else:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
        return line


class TraceJSONLinesExporter(Exporter):
    """Append the flight recorder's ring as JSON lines — one event per line,
    oldest first, each line independently parseable through
    :func:`parse_export_line` (every line carries the ``schema_version``).

    Like :class:`ChromeTraceExporter` this reads from
    ``observability/tracing.py`` rather than the ``report`` argument; with
    neither ``path`` nor ``stream`` the lines are returned as one string.
    """

    def __init__(self, path: Optional[str] = None, stream: Optional[IO[str]] = None):
        self.path = path
        self.stream = stream

    def export(self, report: Mapping[str, Any]) -> str:
        from torchmetrics_tpu.observability import tracing

        lines = []
        for ev in tracing.events():
            payload = ev.as_dict()
            payload["schema_version"] = SCHEMA_VERSION
            lines.append(json.dumps(payload, sort_keys=True, separators=(",", ":")))
        text = "\n".join(lines) + ("\n" if lines else "")
        if self.stream is not None:
            self.stream.write(text)
            try:
                self.stream.flush()
            except Exception:  # pragma: no cover
                pass
        elif self.path is not None:
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(text)
        return text


def _prom_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(**kv: str) -> str:
    inner = ",".join(f'{k}="{_prom_escape(str(v))}"' for k, v in kv.items() if v is not None)
    return "{" + inner + "}" if inner else ""


class PrometheusExporter(Exporter):
    """Render a report in the Prometheus text exposition format (0.0.4).

    ``export`` returns the exposition text; pass ``path=`` to also write it
    atomically enough for a textfile collector (write then rename is the
    collector's job — we just overwrite).
    """

    def __init__(self, namespace: str = "tm_tpu", path: Optional[str] = None):
        self.namespace = namespace
        self.path = path

    def export(self, report: Mapping[str, Any]) -> str:
        ns = self.namespace
        proc = _process_label(report)
        out: List[str] = []
        rows = dict(report.get("metrics", {}))

        for name in COUNTER_NAMES:
            metric_name = f"{ns}_{name}_total"
            out.append(f"# HELP {metric_name} {_COUNTER_HELP.get(name, name)}")
            out.append(f"# TYPE {metric_name} counter")
            family = _COUNTER_FAMILY.get(name)
            for label, row in sorted(rows.items()):
                val = int(row.get("counters", {}).get(name, 0))
                out.append(
                    f"{metric_name}{_labels(metric=label, process=proc, family=family, **{'class': row.get('class', '')})} {val}"
                )

        cache_name = f"{ns}_compile_cache_events_total"
        out.append(f"# HELP {cache_name} Per-metric compile-cache events by entrypoint.")
        out.append(f"# TYPE {cache_name} counter")
        for label, row in sorted(rows.items()):
            for kind, slot in sorted(row.get("cache", {}).items()):
                for event in ("hits", "misses", "traces"):
                    out.append(
                        f"{cache_name}{_labels(metric=label, entrypoint=kind, event=event, process=proc)} "
                        f"{int(slot.get(event, 0))}"
                    )

        span_name = f"{ns}_span_seconds"
        out.append(f"# HELP {span_name} Host-side boundary latency per metric and span.")
        out.append(f"# TYPE {span_name} histogram")
        for label, row in sorted(rows.items()):
            for sname, s in sorted(row.get("spans", {}).items()):
                cum = 0
                for edge_us, n in s.get("buckets", []):
                    cum += int(n)
                    le = "+Inf" if edge_us is None else repr(edge_us / 1e6)
                    out.append(
                        f"{span_name}_bucket{_labels(metric=label, span=sname, le=le, process=proc)} {cum}"
                    )
                out.append(
                    f"{span_name}_sum{_labels(metric=label, span=sname, process=proc)} "
                    f"{repr(float(s.get('total_us', 0.0)) / 1e6)}"
                )
                out.append(
                    f"{span_name}_count{_labels(metric=label, span=sname, process=proc)} {int(s.get('count', 0))}"
                )

        bsync_name = f"{ns}_sync_bucket_measured_seconds_total"
        out.append(
            f"# HELP {bsync_name} Measured (block-until-ready) sync wall time attributed per "
            "collective bucket."
        )
        out.append(f"# TYPE {bsync_name} counter")
        for label, row in sorted(rows.items()):
            for key, b in sorted(row.get("sync_buckets", {}).items()):
                out.append(
                    f"{bsync_name}{_labels(metric=label, bucket=key, process=proc)} "
                    f"{repr(float(b.get('measured_us', 0.0)) / 1e6)}"
                )
        bbytes_name = f"{ns}_sync_bucket_model_bytes_total"
        out.append(
            f"# HELP {bbytes_name} Modelled per-chip bucket traffic: naive 2(n-1)/n vs "
            "granule-aware ring model (compressed wire sizes when a compression mode is "
            "active) vs the uncompressed raw ring model."
        )
        out.append(f"# TYPE {bbytes_name} counter")
        for label, row in sorted(rows.items()):
            for key, b in sorted(row.get("sync_buckets", {}).items()):
                for model, field in (
                    ("naive", "model_naive_bytes"),
                    ("ring", "model_ring_bytes"),
                    ("raw", "model_raw_bytes"),
                ):
                    out.append(
                        f"{bbytes_name}{_labels(metric=label, bucket=key, model=model, process=proc)} "
                        f"{int(b.get(field, 0))}"
                    )
        bcomp_name = f"{ns}_sync_bucket_compression_info"
        out.append(
            f"# HELP {bcomp_name} Active compression mode per collective bucket "
            "(info-style gauge: value is always 1, the mode rides the label)."
        )
        out.append(f"# TYPE {bcomp_name} gauge")
        for label, row in sorted(rows.items()):
            for key, b in sorted(row.get("sync_buckets", {}).items()):
                mode = str(b.get("compression", "none"))
                out.append(f"{bcomp_name}{_labels(metric=label, bucket=key, mode=mode, process=proc)} 1")
        qerr_name = f"{ns}_sync_bucket_quant_rel_err"
        out.append(
            f"# HELP {qerr_name} Measured quantization relative error per compressed bucket "
            "(summary: _sum over measurements, _count measurements)."
        )
        out.append(f"# TYPE {qerr_name} summary")
        for label, row in sorted(rows.items()):
            for key, b in sorted(row.get("sync_buckets", {}).items()):
                if not int(b.get("quant_err_count", 0)):
                    continue
                out.append(
                    f"{qerr_name}_sum{_labels(metric=label, bucket=key, process=proc)} "
                    f"{repr(float(b.get('quant_rel_err_sum', 0.0)))}"
                )
                out.append(
                    f"{qerr_name}_count{_labels(metric=label, bucket=key, process=proc)} "
                    f"{int(b.get('quant_err_count', 0))}"
                )
        bres_name = f"{ns}_sync_bucket_residual_bytes"
        out.append(
            f"# HELP {bres_name} Ring-model minus naive-model bucket bytes (the granule floor "
            "the naive model misses)."
        )
        out.append(f"# TYPE {bres_name} gauge")
        for label, row in sorted(rows.items()):
            for key, b in sorted(row.get("sync_buckets", {}).items()):
                out.append(
                    f"{bres_name}{_labels(metric=label, bucket=key, process=proc)} "
                    f"{int(b.get('residual_bytes', 0))}"
                )

        # live state-HBM rows (observability/memory.py): only metrics with at
        # least one recorded install or snapshot emit samples, so dark jobs
        # add no noise
        mem_rows = {
            label: row["memory"]
            for label, row in rows.items()
            if isinstance(row.get("memory"), Mapping)
            and (
                int(row["memory"].get("installs", 0))
                or int(row["memory"].get("snapshots", 0))
            )
        }
        if mem_rows:
            msb_name = f"{ns}_memory_state_bytes"
            out.append(
                f"# HELP {msb_name} Live metric-state HBM residency (addressable shard "
                "bytes) by watermark: current = last install, peak = high watermark."
            )
            out.append(f"# TYPE {msb_name} gauge")
            for label, mem in sorted(mem_rows.items()):
                for watermark in ("current", "peak"):
                    out.append(
                        f"{msb_name}{_labels(metric=label, watermark=watermark, process=proc)} "
                        f"{int(mem.get(f'{watermark}_bytes', 0))}"
                    )
            mlb_name = f"{ns}_memory_state_leaf_bytes"
            out.append(
                f"# HELP {mlb_name} Per-leaf resident state bytes as of the last install."
            )
            out.append(f"# TYPE {mlb_name} gauge")
            for label, mem in sorted(mem_rows.items()):
                for leaf, lrow in sorted(mem.get("leaves", {}).items()):
                    out.append(
                        f"{mlb_name}{_labels(metric=label, leaf=leaf, process=proc)} "
                        f"{int(lrow.get('bytes', 0))}"
                    )
            mib_name = f"{ns}_memory_install_bytes_total"
            out.append(
                f"# HELP {mib_name} Cumulative state bytes installed, split by install "
                "path (donated = in-place buffer reuse, copied = aliased state)."
            )
            out.append(f"# TYPE {mib_name} counter")
            for label, mem in sorted(mem_rows.items()):
                for path in ("donated", "copied"):
                    out.append(
                        f"{mib_name}{_labels(metric=label, path=path, process=proc)} "
                        f"{int(mem.get(f'{path}_install_bytes', 0))}"
                    )

        cc = report.get("compile_cache", {})
        flat_name = f"{ns}_compile_cache_total"
        out.append(f"# HELP {flat_name} Global compile-cache counters.")
        out.append(f"# TYPE {flat_name} counter")
        for event in ("hits", "misses", "traces", "evictions"):
            if event in cc:
                out.append(f"{flat_name}{_labels(event=event, process=proc)} {int(cc[event])}")
        by = cc.get("by_entrypoint", {})
        if by:
            ep_name = f"{ns}_compile_cache_entrypoint_total"
            out.append(f"# HELP {ep_name} Global compile-cache counters by entrypoint.")
            out.append(f"# TYPE {ep_name} counter")
            for kind, slot in sorted(by.items()):
                for event, val in sorted(slot.items()):
                    if event == "entry_bytes":  # resident size, not monotonic: gauge below
                        continue
                    out.append(f"{ep_name}{_labels(entrypoint=kind, event=event, process=proc)} {int(val)}")
            if any(int(slot.get("entry_bytes", 0)) for slot in by.values()):
                eb_name = f"{ns}_memory_cache_entry_bytes"
                out.append(
                    f"# HELP {eb_name} Resident executable bytes of live compile-cache "
                    "entries by entrypoint (from compiled.memory_analysis(); falls with "
                    "LRU eviction)."
                )
                out.append(f"# TYPE {eb_name} gauge")
                for kind, slot in sorted(by.items()):
                    out.append(
                        f"{eb_name}{_labels(entrypoint=kind, process=proc)} "
                        f"{int(slot.get('entry_bytes', 0))}"
                    )

        # health-monitor payloads (observability/health.py reports) ride the
        # same exposition: alert counters plus a last-value gauge per series
        health = report.get("health")
        if isinstance(health, Mapping):
            h_series = health.get("series", {})
            ha_name = f"{ns}_health_alerts_total"
            out.append(f"# HELP {ha_name} Health-monitor alerts by series and severity.")
            out.append(f"# TYPE {ha_name} counter")
            for sname, row in sorted(h_series.items()):
                for sev, n in sorted(row.get("alerts", {}).items()):
                    out.append(
                        f"{ha_name}{_labels(series=sname, severity=sev, process=proc)} {int(n)}"
                    )
            ho_name = f"{ns}_health_observations_total"
            out.append(f"# HELP {ho_name} Health-monitor observations per series.")
            out.append(f"# TYPE {ho_name} counter")
            for sname, row in sorted(h_series.items()):
                out.append(
                    f"{ho_name}{_labels(series=sname, process=proc)} "
                    f"{int(row.get('observations', 0))}"
                )
            hv_name = f"{ns}_health_last_value"
            out.append(f"# HELP {hv_name} Last observed value per health series.")
            out.append(f"# TYPE {hv_name} gauge")
            for sname, row in sorted(h_series.items()):
                val = row.get("last_value")
                # non-finite values were stringified for JSON; skip them here
                if isinstance(val, (int, float)) and not isinstance(val, bool):
                    out.append(
                        f"{hv_name}{_labels(series=sname, process=proc)} {repr(float(val))}"
                    )

        # autotuner payloads (parallel/autotune.py reports) ride the same
        # exposition: current policy as an info gauge, decision counters
        autotune = report.get("autotune")
        if isinstance(autotune, Mapping):
            pol = autotune.get("policy") or {}
            ap_name = f"{ns}_autotune_policy_info"
            out.append(
                f"# HELP {ap_name} Current sync policy under autotuner control "
                "(info-style gauge: value is always 1, the policy rides the labels)."
            )
            out.append(f"# TYPE {ap_name} gauge")
            out.append(
                f"{ap_name}{_labels(every_n=pol.get('every_n'), at_compute=pol.get('at_compute'), compression=pol.get('compression'), state=autotune.get('state'), process=proc)} 1"
            )
            counts = autotune.get("counts", {})
            at_name = f"{ns}_autotune_transitions_total"
            out.append(
                f"# HELP {at_name} Autotuner state-machine decisions by action "
                "(commits count applied policy switches)."
            )
            out.append(f"# TYPE {at_name} counter")
            for action in ("observations", "proposals", "trials", "commits", "transitions"):
                out.append(
                    f"{at_name}{_labels(action=action, process=proc)} {int(counts.get(action, 0))}"
                )
            av_name = f"{ns}_autotune_vetoes_total"
            out.append(f"# HELP {av_name} Pending commits vetoed by a guardrail.")
            out.append(f"# TYPE {av_name} counter")
            out.append(f"{av_name}{_labels(process=proc)} {int(counts.get('vetoes', 0))}")
            ar_name = f"{ns}_autotune_rollbacks_total"
            out.append(f"# HELP {ar_name} Committed policies rolled back.")
            out.append(f"# TYPE {ar_name} counter")
            out.append(f"{ar_name}{_labels(process=proc)} {int(counts.get('rollbacks', 0))}")

        # memory-report payloads (observability/memory.py memory_report()):
        # executable analyses per fingerprint, aggregated cost, and the
        # ShardingAdvisor's replication-waste advisory
        memory = report.get("memory")
        if isinstance(memory, Mapping) and (
            memory.get("executables") or memory.get("cost") or memory.get("advice")
        ):
            mx_name = f"{ns}_memory_executable_bytes"
            out.append(
                f"# HELP {mx_name} Compiled-executable section sizes per cache entry "
                "(compiled.memory_analysis(); peak section only on backends that report "
                "peak HBM)."
            )
            out.append(f"# TYPE {mx_name} gauge")
            for erow in memory.get("executables", []):
                fp = erow.get("fingerprint_hash") or f"({erow.get('kind') or 'unkeyed'})"
                for section, val in sorted(erow.get("memory", {}).items()):
                    # argument_bytes -> section="argument"
                    out.append(
                        f"{mx_name}{_labels(fingerprint=fp, kind=erow.get('kind'), section=section.rsplit('_bytes', 1)[0], process=proc)} "
                        f"{int(val)}"
                    )
            cost = memory.get("cost", {})
            cf_name = f"{ns}_cost_flops"
            out.append(
                f"# HELP {cf_name} XLA cost_analysis() FLOPs of live cache entries per "
                "config fingerprint."
            )
            out.append(f"# TYPE {cf_name} gauge")
            for fp, slot in sorted(cost.items()):
                out.append(
                    f"{cf_name}{_labels(fingerprint=fp, process=proc)} "
                    f"{repr(float(slot.get('flops', 0.0)))}"
                )
            cb_name = f"{ns}_cost_bytes_accessed"
            out.append(
                f"# HELP {cb_name} XLA cost_analysis() bytes accessed of live cache "
                "entries per config fingerprint."
            )
            out.append(f"# TYPE {cb_name} gauge")
            for fp, slot in sorted(cost.items()):
                out.append(
                    f"{cb_name}{_labels(fingerprint=fp, process=proc)} "
                    f"{repr(float(slot.get('bytes_accessed', 0.0)))}"
                )
            advice = memory.get("advice")
            if isinstance(advice, Mapping):
                mw_name = f"{ns}_memory_replicated_waste_bytes"
                out.append(
                    f"# HELP {mw_name} Replicated psum-state HBM waste per candidate leaf "
                    "(leaf bytes x (n_devices - 1)); the ShardingAdvisor's ranking."
                )
                out.append(f"# TYPE {mw_name} gauge")
                for cand in advice.get("candidates", []):
                    out.append(
                        f"{mw_name}{_labels(metric=cand.get('metric'), leaf=cand.get('leaf'), process=proc)} "
                        f"{int(cand.get('replicated_waste_bytes', 0))}"
                    )

        # gather-report payloads (observability/gathers.py gather_report()):
        # live cat-state growth, pod-scale projections, and advisor advice
        gather = report.get("gather")
        if isinstance(gather, Mapping) and (
            gather.get("metrics") or gather.get("projection") or gather.get("advice")
        ):
            gb_name = f"{ns}_gather_cat_bytes_total"
            out.append(
                f"# HELP {gb_name} Cumulative unpadded cat-state bytes appended per "
                "metric (live gather-plane attribution)."
            )
            out.append(f"# TYPE {gb_name} counter")
            for label, g in sorted(gather.get("metrics", {}).items()):
                out.append(
                    f"{gb_name}{_labels(metric=label, process=proc)} "
                    f"{int(g.get('cat_bytes', 0))}"
                )
            ge_name = f"{ns}_gather_cat_ew_bytes_per_step"
            out.append(
                f"# HELP {ge_name} Exponentially-weighted cat-state growth rate in "
                "bytes per update step."
            )
            out.append(f"# TYPE {ge_name} gauge")
            for label, g in sorted(gather.get("metrics", {}).items()):
                out.append(
                    f"{ge_name}{_labels(metric=label, process=proc)} "
                    f"{repr(float(g.get('ew_bytes_per_step', 0.0)))}"
                )
            gh_name = f"{ns}_gather_cat_hwm_bytes"
            out.append(
                f"# HELP {gh_name} Cat-state high-watermark: the largest running "
                "unpadded cat size observed."
            )
            out.append(f"# TYPE {gh_name} gauge")
            for label, g in sorted(gather.get("metrics", {}).items()):
                out.append(
                    f"{gh_name}{_labels(metric=label, process=proc)} "
                    f"{int(g.get('hwm_bytes', 0))}"
                )
            gp_name = f"{ns}_gather_projected_bytes_per_chip_per_step"
            out.append(
                f"# HELP {gp_name} Pod-scale flat all-gather projection of live "
                "cat-state attribution, per metric and mesh size."
            )
            out.append(f"# TYPE {gp_name} gauge")
            for n_chips, proj in sorted(
                gather.get("projection", {}).items(), key=lambda kv: int(kv[0])
            ):
                for label, mrow in sorted(proj.get("metrics", {}).items()):
                    out.append(
                        f"{gp_name}{_labels(metric=label, n_chips=n_chips, process=proc)} "
                        f"{int(mrow.get('projected_bytes_per_chip_per_step', 0))}"
                    )
            advice = gather.get("advice")
            if isinstance(advice, Mapping):
                ga_name = f"{ns}_gather_advice_info"
                out.append(
                    f"# HELP {ga_name} GatherAdvisor recommendation per cat-state "
                    "consumer (info-style gauge: value is always 1, the "
                    "recommendation rides the labels)."
                )
                out.append(f"# TYPE {ga_name} gauge")
                for cand in advice.get("candidates", []):
                    out.append(
                        f"{ga_name}{_labels(metric=cand.get('metric'), recommendation=cand.get('recommendation'), n_chips=str(advice.get('n_chips')), process=proc)} 1"
                    )
                gc_name = f"{ns}_gather_advice_cut_bytes_per_chip_per_step"
                out.append(
                    f"# HELP {gc_name} Modelled per-chip byte cut per advisor route: "
                    "two_stage = flat minus the DCN-exchange cost, sketch = the whole "
                    "projected gather (a fixed-shape state rides the psum family)."
                )
                out.append(f"# TYPE {gc_name} gauge")
                for cand in advice.get("candidates", []):
                    for route, field in (
                        ("two_stage", "two_stage_cut_bytes_per_chip_per_step"),
                        ("sketch", "sketch_cut_bytes_per_chip_per_step"),
                    ):
                        out.append(
                            f"{gc_name}{_labels(metric=cand.get('metric'), route=route, process=proc)} "
                            f"{int(cand.get(field, 0))}"
                        )

        # accuracy attestations (observability/accuracy.py): per-metric-row
        # ``attestation`` blocks on registry reports, plus the attestations /
        # ledger of a ``kind: "attestation"`` accuracy_report() payload
        attestations: Dict[str, Mapping[str, Any]] = {
            label: row["attestation"]
            for label, row in rows.items()
            if isinstance(row.get("attestation"), Mapping)
        }
        accuracy = report.get("accuracy")
        if isinstance(accuracy, Mapping):
            for label, att in accuracy.get("attestations", {}).items():
                if isinstance(att, Mapping):
                    attestations[str(label)] = att
        if attestations:
            ab_name = f"{ns}_accuracy_error_bound"
            out.append(
                f"# HELP {ab_name} Composed worst-case error bound attested for the "
                "metric's last computed value (0 for exact-path values)."
            )
            out.append(f"# TYPE {ab_name} gauge")
            for label, att in sorted(attestations.items()):
                out.append(
                    f"{ab_name}{_labels(metric=label, exact=str(bool(att.get('exact', False))).lower(), process=proc)} "
                    f"{repr(float(att.get('bound', 0.0)))}"
                )
            burn_name = f"{ns}_accuracy_budget_burn"
            out.append(
                f"# HELP {burn_name} Error-budget burn per provenance source: predicted "
                "bound over declared budget (1.0 = budget fully consumed)."
            )
            out.append(f"# TYPE {burn_name} gauge")
            for label, att in sorted(attestations.items()):
                for lrow in att.get("ledger", ()):
                    if lrow.get("burn") is None:
                        continue
                    out.append(
                        f"{burn_name}{_labels(metric=label, source=lrow.get('source'), process=proc)} "
                        f"{repr(float(lrow['burn']))}"
                    )
            wb_name = f"{ns}_accuracy_within_budget"
            out.append(
                f"# HELP {wb_name} Whether every budgeted provenance source's predicted "
                "bound fits its declared budget (1 = within, 0 = over; sources without "
                "a declared budget emit nothing)."
            )
            out.append(f"# TYPE {wb_name} gauge")
            for label, att in sorted(attestations.items()):
                wb = att.get("within_budget")
                if wb is None:
                    continue
                out.append(f"{wb_name}{_labels(metric=label, process=proc)} {int(bool(wb))}")
            obs_name = f"{ns}_accuracy_observed_err"
            out.append(
                f"# HELP {obs_name} Observed |approx - exact| relative error from the "
                "latest shadow-exact audit (only metrics with an audited attestation)."
            )
            out.append(f"# TYPE {obs_name} gauge")
            for label, att in sorted(attestations.items()):
                if att.get("observed_err") is None:
                    continue
                out.append(
                    f"{obs_name}{_labels(metric=label, process=proc)} "
                    f"{repr(float(att['observed_err']))}"
                )

        text = "\n".join(out) + "\n"
        if self.path is not None:
            with open(self.path, "w", encoding="utf-8") as fh:
                fh.write(text)
        return text


class ChromeTraceExporter(Exporter):
    """Export the flight recorder's ring as Chrome trace-event JSON.

    Unlike the other backends this reads from ``observability/tracing.py``
    (the recorder must be armed to have captured anything); the ``report``
    argument only contributes its global counters to the trace's
    ``otherData`` so a trace file is self-describing.  ``export`` returns the
    JSON text and, with ``path=``, also writes it to disk — the file loads
    directly in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path

    def export(self, report: Mapping[str, Any]) -> str:
        from torchmetrics_tpu.observability import tracing

        meta: Dict[str, Any] = {}
        glob = report.get("global", {}) if isinstance(report, Mapping) else {}
        counters = {k: v for k, v in glob.get("counters", {}).items() if v}
        if counters:
            meta["report_counters"] = counters
        text = json.dumps(tracing.chrome_trace(meta or None), separators=(",", ":"))
        if self.path is not None:
            with open(self.path, "w", encoding="utf-8") as fh:
                fh.write(text)
        return text


_FMT_ALIASES = {
    "log": LoggingExporter,
    "logging": LoggingExporter,
    "jsonl": JSONLinesExporter,
    "json": JSONLinesExporter,
    "prometheus": PrometheusExporter,
    "prom": PrometheusExporter,
    "chrome": ChromeTraceExporter,
    "chrome-trace": ChromeTraceExporter,
    "perfetto": ChromeTraceExporter,
    "trace": ChromeTraceExporter,
    "trace-jsonl": TraceJSONLinesExporter,
}


def export(
    report: Optional[Mapping[str, Any]] = None,
    fmt: str = "log",
    exporter: Optional[Exporter] = None,
    **kwargs: Any,
) -> Any:
    """Export a telemetry report through one of the built-in backends.

    ``report`` defaults to a fresh :func:`registry.report` snapshot.  Either
    name a backend (``fmt`` in ``log | jsonl | prometheus | chrome``, with ``kwargs``
    forwarded to its constructor) or pass a ready ``exporter`` instance.
    Returns whatever the backend's ``export`` returns (the JSON line, the
    exposition text, or ``None`` for logging).
    """
    if report is None:
        from torchmetrics_tpu.observability.registry import report as _report

        report = _report()
    if exporter is None:
        try:
            cls = _FMT_ALIASES[fmt]
        except KeyError:
            raise ValueError(
                f"unknown telemetry export format {fmt!r}; expected one of "
                f"{sorted(set(_FMT_ALIASES))} (or pass `exporter=`)"
            ) from None
        exporter = cls(**kwargs)
    return exporter.export(report)
