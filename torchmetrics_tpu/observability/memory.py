"""Memory & cost observability plane: per-metric HBM attribution, compiled-
executable memory/cost analysis, and a report-only :class:`ShardingAdvisor`.

The sync planes (PRs 6-11) made *wire* bytes measurable; this module does the
same for *resident* bytes, in three attribution layers:

1. **Live state-HBM accounting** — every state install (the pytree rebound to
   ``metric._state`` by update/forward/restore) is sized per-leaf and folded
   into the telemetry registry as current/peak watermarks plus a
   donated-vs-copied install byte split.  Sizing is *sharded-aware*: a leaf's
   resident bytes are its per-shard bytes times its **addressable** device
   count (what this host's HBM actually holds), not its logical bytes — a
   replicated (2048, 2048) float32 on 8 local devices really occupies
   8 x 16 MiB.  The sizer reads only aval metadata (shape/dtype/sharding),
   never device buffers, so the armed path cannot retrace.
2. **Compiled-executable analysis** — while armed, every compile-cache entry
   in ``core/compile.py`` records ``compiled.memory_analysis()`` (argument /
   output / temp / generated-code bytes, plus peak HBM where the backend
   reports it) and ``cost_analysis()`` (FLOPs, bytes accessed), keyed by the
   same 12-hex config fingerprints as ``compile_timeline()``.  Surfaced via
   :func:`memory_timeline` / :func:`cost_by_fingerprint`; backends without
   analyses (CPU reports no peak) degrade to whatever fields exist, with
   ``available`` flagging rows where analysis failed entirely.
3. **Replication-waste attribution & actuation** — each psum-family state
   leaf is replicated across the mesh by default, wasting
   ``leaf_bytes x (n_devices - 1)`` of cluster HBM.  The
   :class:`ShardingAdvisor` ranks those leaves and quotes, per candidate, the
   granule-aware ring all-reduce bytes it pays now versus the reduce-scatter
   bytes it would pay sharded (arxiv 2004.13336's weight-update sharding
   applied to metric state).  ``advise()`` stays report-only;
   :meth:`ShardingAdvisor.recommend` closes the loop: with ``apply=True`` it
   drives a propose→arm→commit state machine (mirroring
   :class:`~torchmetrics_tpu.parallel.autotune.SyncAutotuner`) that installs
   ``state_sharding`` specs via ``Metric.set_state_sharding``, ledgers every
   decision as ``kind: "sharding_decision"`` JSONL rows, audits the expected
   one-time retraces against ``cache_stats_since``, and is veto-able /
   roll-back-able through :meth:`ShardingAdvisor.guardrail_sink`.

Everything is double-gated: :func:`enable_memory_telemetry` arms the plane,
but nothing records until ``observability.enable()`` is also on (mirroring
the flight recorder).  Arming adds **zero retraces and zero cache entries**:
state sizing happens outside traced code, and executable analysis re-lowers
through jax's jaxpr cache (the traced body does not re-run; the one-off cost
is a second XLA compile per entry while armed).  Proven by the jaxpr
bit-identity and ``cache_stats`` delta tests in ``test_memory.py``.

Quick tour::

    from torchmetrics_tpu import observability as obs
    from torchmetrics_tpu.observability import memory

    obs.enable()
    memory.enable_memory_telemetry()      # or TM_TPU_MEMORY_TELEMETRY=1
    ...                                   # train; installs are sized live
    acc.telemetry.as_dict()["memory"]     # watermarks + per-leaf bytes
    memory.memory_timeline()              # per-entry executable analyses
    memory.cost_by_fingerprint()          # FLOPs/bytes by config fingerprint
    advice = memory.ShardingAdvisor().advise([fid, psnr])
    advice["candidates"][0]               # biggest replicated-waste leaf
    obs.export(memory.memory_report([fid, psnr]), fmt="jsonl")

A cheap, device-free example (the doctest tier-1 actually runs)::

    >>> from torchmetrics_tpu.classification import MulticlassConfusionMatrix
    >>> from torchmetrics_tpu.observability.memory import ShardingAdvisor
    >>> m = MulticlassConfusionMatrix(num_classes=64)
    >>> advice = ShardingAdvisor().advise([m], n_devices=8)
    >>> [c["leaf"] for c in advice["candidates"]]
    ['confmat']
    >>> advice["candidates"][0]["replicated_waste_bytes"] == 64 * 64 * 4 * 7
    True
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

import jax

from torchmetrics_tpu.core import compile as _compile
from torchmetrics_tpu.core.compile import cost_by_fingerprint, memory_timeline
from torchmetrics_tpu.observability import registry
from torchmetrics_tpu.utilities.benchmark import (
    RING_GRANULE_BYTES,
    _is_psum_shaped,
    reduce_scatter_bytes,
    ring_reduce_bytes,
)

__all__ = [
    "SHARDING_ACTIONS",
    "SHARDING_LEDGER_KIND",
    "SHARDING_STATES",
    "ShardingAdvisor",
    "cost_by_fingerprint",
    "disable_memory_telemetry",
    "enable_memory_telemetry",
    "leaf_resident_bytes",
    "memory_report",
    "memory_telemetry_enabled",
    "memory_timeline",
    "snapshot_metric",
    "state_memory_rows",
]

_log = logging.getLogger("torchmetrics_tpu.observability")

#: the actuation state machine's states, in commit order (mirrors
#: ``parallel.autotune.AUTOTUNE_STATES``)
SHARDING_STATES = ("observe", "candidate", "trial", "committed")
#: every action a sharding ledger entry may carry
SHARDING_ACTIONS = ("propose", "arm", "commit", "veto", "rollback", "audit")
#: ``kind`` stamp on every sharding-decision ledger entry (JSONL consumers
#: filter on it exactly like ``autotune_decision``)
SHARDING_LEDGER_KIND = "sharding_decision"


# ---------------------------------------------------------------------------
# layer 1: live state-HBM sizing
# ---------------------------------------------------------------------------


def leaf_resident_bytes(leaf: Any) -> Tuple[int, int]:
    """``(resident_bytes, logical_bytes)`` of one array-like leaf.

    Logical bytes are ``size x itemsize``.  Resident bytes are what this
    host's HBM holds: per-shard bytes times the sharding's **addressable**
    device count — so a fully replicated leaf on 8 local devices counts 8x
    its logical bytes, while a leaf sharded 8 ways counts exactly once.
    Falls back to logical bytes when the leaf has no sharding (tracers,
    numpy, scalars mid-trace).  Reads only metadata, never device buffers.
    """
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0, 0
    try:
        itemsize = int(dtype.itemsize)
    except AttributeError:
        import numpy as np

        itemsize = int(np.dtype(dtype).itemsize)
    logical = int(math.prod(shape)) * itemsize
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
            n_addressable = len(sharding.addressable_devices)
            return int(math.prod(shard_shape)) * itemsize * n_addressable, logical
        except Exception:  # tracers expose .sharding without a concrete mesh
            pass
    return logical, logical


def state_memory_rows(state: Any) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Size a state pytree into ``({leaf_name: {"bytes", "logical_bytes"}},
    resident_total)`` — the sizer the registry calls on every install.

    Dict states (the ``Metric._state`` layout) keep their top-level names, so
    leaf rows line up with the reduction table; nested pytree leaves (sketch
    states) are summed under their top-level name.  Non-dict pytrees fall
    back to jax tree-path names.
    """
    if isinstance(state, Mapping):
        items: Iterable[Tuple[str, Any]] = state.items()
    else:
        items = [
            (jax.tree_util.keystr(path), leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]
        ]
    leaves: Dict[str, Dict[str, int]] = {}
    resident_total = 0
    for name, sub in items:
        resident = logical = 0
        for leaf in jax.tree.leaves(sub):
            r, l = leaf_resident_bytes(leaf)
            resident += r
            logical += l
        if resident or logical:
            leaves[str(name)] = {"bytes": resident, "logical_bytes": logical}
            resident_total += resident
    return leaves, resident_total


def snapshot_metric(metric: Any) -> None:
    """Record ``metric``'s *current* state residency into the registry right
    now, without waiting for the next install — useful when arming after the
    metric already accumulated state.  Counted as a snapshot, not an install.
    Same double gate as install accounting; a no-op while unarmed."""
    state = getattr(metric, "_state", None)
    if state:
        registry.record_state_snapshot(metric, state)


# ---------------------------------------------------------------------------
# arming (the second half of the double gate)
# ---------------------------------------------------------------------------


def enable_memory_telemetry() -> None:
    """Arm the memory plane: live install sizing in the registry plus
    per-entry executable analysis capture in the compile cache.

    Nothing records until ``observability.enable()`` is also on.  Arming
    changes no cache key and adds no retrace: sizing reads aval metadata
    outside traced code, and executable analysis re-lowers each entry through
    jax's shared jaxpr cache (the Python body does not re-run; the cost is
    one extra XLA compile per new entry while armed)."""
    registry.set_memory_sizer(state_memory_rows)
    registry.set_memory_armed(True)
    _compile.set_analysis_capture(True)


def disable_memory_telemetry() -> None:
    """Disarm the memory plane.  Recorded watermarks and analysis rows are
    kept (clear them with ``reset_telemetry()`` / ``clear_compile_cache()``);
    new installs and new cache entries stop being sized."""
    registry.set_memory_armed(False)
    _compile.set_analysis_capture(False)


def memory_telemetry_enabled() -> bool:
    """True while the memory plane is armed (the registry gate; executable
    capture is armed and disarmed in lockstep)."""
    return registry.memory_armed()


# ---------------------------------------------------------------------------
# layer 3: replication-waste attribution
# ---------------------------------------------------------------------------


class ShardingAdvisor:
    """Advisor ranking the state leaves worth sharding — and, through
    :meth:`recommend`, the actuator that installs the specs.

    For each psum-family leaf (the reductions ``core.reductions.sync_leaf``
    lowers to a ring all-reduce) of each metric, computes:

    * ``replicated_waste_bytes`` — ``leaf_bytes x (n_devices - 1)``, the
      cluster HBM spent on redundant replicas today;
    * ``ring_allreduce_bytes_per_chip`` — granule-aware per-chip wire bytes
      one combine pays while replicated (``utilities.benchmark``'s model);
    * ``reduce_scatter_bytes_per_chip`` — what the same combine would pay
      with the leaf reduce-scattered (exactly the scatter half of the ring);
    * ``projected_wire_savings_bytes_per_chip`` — the difference.

    Leaf bytes come from the live registry rows when the memory plane has
    recorded them (``source: "registry"`` — this is how the bench reproduces
    BENCH_r05's FID+PSNR 33,570,840-byte figure from live attribution), else
    from the metric's state pytree directly (``source: "state"``).  Gather-
    family leaves (cat/reservoir/structural sketches) are excluded: they are
    not replicated-by-sum, so sharding them is a different problem.

    :meth:`advise` is report-only by construction: it never touches
    placement.  Its output dict is what ``memory_report()`` exports under
    ``memory.advice``.  :meth:`recommend` wraps it in the actuation state
    machine (``observe → candidate → trial → committed``, mirroring
    :class:`~torchmetrics_tpu.parallel.autotune.SyncAutotuner`): a commit
    installs each recommended leaf's :class:`~torchmetrics_tpu.core.reductions.ShardSpec`
    on its metric, flips the metric's config fingerprint (one expected
    ``new-key`` compile-cache miss per metric, audited by
    :meth:`retrace_report`), and every transition lands in
    :meth:`decision_ledger` as an ``autotune_decision``-shaped row with
    ``kind: "sharding_decision"``.
    """

    def __init__(
        self,
        n_devices: Optional[int] = None,
        granule: int = RING_GRANULE_BYTES,
        min_leaf_bytes: int = 1 << 20,
        veto_severity: str = "warning",
    ) -> None:
        self.n_devices = n_devices
        self.granule = int(granule)
        #: leaves at or above this size make the ``recommended`` short list;
        #: below it the granule floor erodes the reduce-scatter win and the
        #: HBM recovered is noise
        self.min_leaf_bytes = int(min_leaf_bytes)
        #: health alerts at/above this severity veto a pending trial or roll
        #: back a committed sharding (see :meth:`guardrail_sink`)
        self.veto_severity = veto_severity
        self.state = "observe"
        self._seq = 0
        self._ledger: List[Dict[str, Any]] = []
        self._candidate: Optional[Dict[str, Any]] = None
        #: per-leaf specs to restore on rollback: ``[(metric, label, leaf, old)]``
        self._previous: Optional[List[Tuple[Any, str, str, Any]]] = None
        self._commit_cache_baseline: Optional[Dict[str, Any]] = None
        self._expected_retraces: Dict[str, Any] = {"new_keys": 0, "causes": []}
        self.counts: Dict[str, int] = {
            "proposals": 0,
            "trials": 0,
            "commits": 0,
            "vetoes": 0,
            "rollbacks": 0,
        }

    @staticmethod
    def _label_for(metric: Any) -> str:
        t = registry.telemetry_for(metric, create=False)
        return t.label if t is not None else type(metric).__name__

    @staticmethod
    def _live_leaves_for(metric: Any) -> Optional[Dict[str, Dict[str, int]]]:
        t = registry.telemetry_for(metric, create=False)
        if t is None:
            return None
        leaves = t.memory.get("leaves")
        return dict(leaves) if leaves else None

    def advise(
        self,
        metrics: Iterable[Union[Any, Tuple[str, Any]]],
        n_devices: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rank every psum-family leaf of ``metrics`` by replicated waste.

        ``metrics`` holds metric instances or ``(label, metric)`` pairs;
        unlabelled metrics take their telemetry label (or class name).
        ``n_devices`` defaults to the advisor's, then ``jax.device_count()``.
        """
        n = int(n_devices or self.n_devices or jax.device_count())
        candidates: List[Dict[str, Any]] = []
        total_psum = 0
        for item in metrics:
            if isinstance(item, tuple) and len(item) == 2 and isinstance(item[0], str):
                label, metric = item
            else:
                label, metric = self._label_for(item), item
            reductions = getattr(metric, "_reductions", None) or {}
            state = getattr(metric, "_state", None) or {}
            live = self._live_leaves_for(metric)
            for name, reduce in sorted(reductions.items()):
                if name not in state or not _is_psum_shaped(reduce):
                    continue
                row = (live or {}).get(name)
                if row and row.get("logical_bytes"):
                    nbytes = int(row["logical_bytes"])
                    source = "registry"
                else:
                    nbytes = sum(
                        leaf_resident_bytes(leaf)[1] for leaf in jax.tree.leaves(state[name])
                    )
                    source = "state"
                if nbytes <= 0:
                    continue
                ring = ring_reduce_bytes(nbytes, n, self.granule)
                scatter = reduce_scatter_bytes(nbytes, n, self.granule)
                candidates.append(
                    {
                        "metric": label,
                        "leaf": name,
                        "bytes": nbytes,
                        "source": source,
                        "replicated_waste_bytes": nbytes * (n - 1),
                        "ring_allreduce_bytes_per_chip": ring,
                        "reduce_scatter_bytes_per_chip": scatter,
                        "projected_wire_savings_bytes_per_chip": ring - scatter,
                        "worth_sharding": nbytes >= self.min_leaf_bytes,
                    }
                )
                total_psum += nbytes
        candidates.sort(key=lambda c: (-c["replicated_waste_bytes"], c["metric"], c["leaf"]))
        return {
            "kind": "sharding_advice",
            "n_devices": n,
            "granule_bytes": self.granule,
            "min_leaf_bytes": self.min_leaf_bytes,
            "total_psum_state_bytes": total_psum,
            "total_replicated_waste_bytes": total_psum * (n - 1),
            "total_ring_allreduce_bytes_per_chip": sum(
                c["ring_allreduce_bytes_per_chip"] for c in candidates
            ),
            "total_reduce_scatter_bytes_per_chip": sum(
                c["reduce_scatter_bytes_per_chip"] for c in candidates
            ),
            "total_projected_wire_savings_bytes_per_chip": sum(
                c["projected_wire_savings_bytes_per_chip"] for c in candidates
            ),
            "candidates": candidates,
            "recommended": [
                f"{c['metric']}/{c['leaf']}" for c in candidates if c["worth_sharding"]
            ],
            "note": (
                "report-only: states stay replicated until the cross-replica "
                "sharding planner lands; candidates ranked by replicated HBM waste"
            ),
        }

    # --------------------------------------------------------- actuation loop
    def recommend(
        self,
        metrics: Iterable[Union[Any, Tuple[str, Any]]],
        n_devices: Optional[int] = None,
        apply: bool = False,
        leaves: Optional[Iterable[str]] = None,
        axis: int = 0,
    ) -> Dict[str, Any]:
        """:meth:`advise` promoted to a proposal: rank the leaves, stage the
        ``worth_sharding`` short list as per-leaf
        :class:`~torchmetrics_tpu.core.reductions.ShardSpec` candidates, and
        (with ``apply=True``) arm and commit them onto the live metrics.

        ``leaves`` restricts the staged set to the named ``"label/leaf"``
        pairs (default: everything ``advise`` recommends); ``axis`` is the
        shard axis every staged spec uses.  Returns the advice payload
        (``kind: "sharding_advice"``, ready for the export front door — the
        JSONL line picks up ``schema_version`` + process stamps and parses
        back through ``parse_export_line``) extended with an ``actuation``
        block recording the staged targets, state-machine state, and — after
        an ``apply=True`` commit — the per-leaf install outcomes.

        Without ``apply`` the state machine stops in ``candidate``: call
        :meth:`arm` then :meth:`commit` to apply by hand, exactly like the
        sync autotuner's staged flow.
        """
        from torchmetrics_tpu.core.reductions import ShardSpec

        pairs: List[Tuple[str, Any]] = []
        for item in metrics:
            if isinstance(item, tuple) and len(item) == 2 and isinstance(item[0], str):
                pairs.append(item)
            else:
                pairs.append((self._label_for(item), item))
        advice = self.advise(pairs, n_devices=n_devices)
        by_label = dict(pairs)
        wanted = set(leaves) if leaves is not None else None
        targets: List[Tuple[str, Any, str, ShardSpec]] = []
        for key in advice["recommended"]:
            if wanted is not None and key not in wanted:
                continue
            label, leaf = key.rsplit("/", 1)
            metric = by_label.get(label)
            if metric is not None:
                targets.append((label, metric, leaf, ShardSpec(axis=axis)))
        prior = self.state
        self._candidate = {
            "advice": advice,
            "targets": targets,
            "n_devices": advice["n_devices"],
        }
        self.state = "candidate"
        self.counts["proposals"] += 1
        self._record(
            "propose",
            state_from=prior,
            targets=[f"{label}/{leaf}" for label, _, leaf, _ in targets],
            trigger={
                "n_devices": advice["n_devices"],
                "total_replicated_waste_bytes": advice["total_replicated_waste_bytes"],
                "projected_wire_savings_bytes_per_chip": advice[
                    "total_projected_wire_savings_bytes_per_chip"
                ],
            },
            rationale=(
                f"staged {len(targets)} leaf spec(s) at/above "
                f"{self.min_leaf_bytes} bytes, ranked by replicated HBM waste"
            ),
        )
        out = dict(advice)
        out["actuation"] = {
            "state": self.state,
            "targets": [f"{label}/{leaf}" for label, _, leaf, _ in targets],
            "applied": False,
        }
        if apply:
            self.arm()
            entry = self.commit()
            out["actuation"] = {
                "state": self.state,
                "targets": entry["targets"],
                "applied": bool(entry["applied"]),
                "skipped": entry["trigger"].get("skipped", []),
                "expected_retraces": entry.get("expected_retraces"),
            }
        return out

    def arm(self) -> Dict[str, Any]:
        """Stage the proposed specs for commit: enter ``trial``, during which
        any guardrail alert vetoes the pending sharding before it applies."""
        if self.state != "candidate" or self._candidate is None:
            raise RuntimeError(
                f"ShardingAdvisor.arm: no candidate to stage (state {self.state!r}); "
                "call recommend() first"
            )
        self.state = "trial"
        self.counts["trials"] += 1
        return self._record(
            "arm",
            state_from="candidate",
            targets=[f"{l}/{leaf}" for l, _, leaf, _ in self._candidate["targets"]],
            rationale="candidate specs staged; guardrails may veto until commit()",
        )

    def commit(self) -> Dict[str, Any]:
        """Install the staged specs on the live metrics.

        Each install goes through ``Metric.set_state_sharding`` — a leaf the
        metric refuses (non-SUM reduction, guarded nan strategy, custom
        ``sync_states``) is skipped and recorded, never silently forced.  The
        compile-cache baseline is captured first so :meth:`retrace_report`
        can prove the transition cost exactly its expected one ``new-key``
        miss per re-fingerprinted metric and nothing more (0 steady-state
        retraces).
        """
        if self.state != "trial" or self._candidate is None:
            raise RuntimeError(
                f"ShardingAdvisor.commit: no staged trial (state {self.state!r}) — "
                "it may have been vetoed by a guardrail; check decision_ledger()"
            )
        from torchmetrics_tpu.core.compile import cache_stats

        self._commit_cache_baseline = cache_stats()
        previous: List[Tuple[Any, str, str, Any]] = []
        applied: List[str] = []
        skipped: List[Dict[str, str]] = []
        for label, metric, leaf, spec in self._candidate["targets"]:
            old = metric.state_shardings.get(leaf)
            try:
                metric.set_state_sharding(leaf, spec)
            except (ValueError, KeyError) as err:
                skipped.append({"target": f"{label}/{leaf}", "error": str(err)})
                continue
            previous.append((metric, label, leaf, old))
            applied.append(f"{label}/{leaf}")
        expected = {
            "new_keys": len({id(m) for m, _, _, _ in previous}),
            # a re-fingerprint of an already-compiled metric attributes as
            # "invalidation" (same entrypoint+signature, new config); a metric
            # first compiled after the commit attributes as "new-key"
            "causes": ["invalidation", "new-key"] if previous else [],
            "entrypoint": None,  # whichever entrypoint next runs the metric
        }
        self._previous = previous
        self._expected_retraces = expected
        self.state = "committed"
        self.counts["commits"] += 1
        entry = self._record(
            "commit",
            state_from="trial",
            targets=applied,
            applied=bool(applied),
            trigger={
                "applied": applied,
                "skipped": skipped,
                "n_devices": self._candidate["n_devices"],
            },
            expected_retraces=expected,
            rationale=(
                f"installed {len(applied)} sharding spec(s); each re-fingerprints "
                "its metric for exactly one new-key compile per entrypoint"
                if applied
                else "no leaf accepted a spec; nothing installed"
            ),
        )
        self._candidate = None
        return entry

    def veto(self, reason: str = "manual", alert: Optional[Any] = None) -> Dict[str, Any]:
        """Veto the pending trial (guardrails call this through
        :meth:`guardrail_sink`; callers may veto manually)."""
        if self.state != "trial":
            raise RuntimeError(
                f"ShardingAdvisor.veto: no pending trial to veto (state {self.state!r})"
            )
        return self._veto(reason, alert=alert)

    def rollback(
        self,
        reason: str = "manual",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Restore every committed leaf's previous sharding (usually
        ``"replicated"``) and ledger why.  The restore re-fingerprints the
        metrics again — the replicated traces are still cached, so going back
        is hit-only."""
        if self.state != "committed" or self._previous is None:
            raise RuntimeError(
                f"ShardingAdvisor.rollback: nothing committed to roll back "
                f"(state {self.state!r})"
            )
        restored = []
        for metric, label, leaf, old in self._previous:
            metric.set_state_sharding(leaf, old if old is not None else "replicated")
            restored.append(f"{label}/{leaf}")
        self.counts["rollbacks"] += 1
        entry = self._record(
            "rollback",
            state_from="committed",
            state_to="observe",
            targets=restored,
            applied=True,
            alert=alert,
            error=error,
            rationale=f"rolled back committed sharding: {reason}",
        )
        self.state = "observe"
        self._previous = None
        return entry

    def guardrail_sink(self, min_severity: Optional[str] = None) -> Any:
        """An ``AlertSink`` wiring :class:`~torchmetrics_tpu.observability.health.HealthMonitor`
        alerts into the loop: ``monitor.add_sink(advisor.guardrail_sink())``.
        Alerts at/above ``min_severity`` (default: the advisor's
        ``veto_severity``) veto a pending trial or roll back a committed
        sharding, in-band — the same guardrail contract as the sync
        autotuner's."""
        from torchmetrics_tpu.observability.health import CallbackAlertSink, _severity_rank

        severity = self.veto_severity if min_severity is None else min_severity
        _severity_rank(severity)  # validates
        return CallbackAlertSink(self._on_alert, min_severity=severity)

    def _on_alert(self, alert: Any) -> None:
        if self.state == "trial":
            self._veto("health_alert", alert=alert)
        elif self.state == "committed" and self._previous is not None:
            self.rollback(reason="health_alert", alert=alert)

    def _veto(
        self, reason: str, alert: Optional[Any] = None, error: Optional[str] = None
    ) -> Dict[str, Any]:
        staged = self._candidate["targets"] if self._candidate else []
        self.counts["vetoes"] += 1
        entry = self._record(
            "veto",
            state_from=self.state,
            state_to="observe",
            targets=[f"{l}/{leaf}" for l, _, leaf, _ in staged],
            applied=False,
            alert=alert,
            error=error,
            rationale=f"pending sharding vetoed: {reason}",
        )
        self.state = "observe"
        self._candidate = None
        return entry

    def retrace_report(self) -> Dict[str, Any]:
        """Compile-cache delta since the last commit, judged against the
        ledgered expectation — the proof that a sharding transition costs
        exactly one ``new-key`` miss per re-fingerprinted metric and that
        steady state re-traces **zero** times.  Ledgered as an ``audit``
        decision."""
        from torchmetrics_tpu.core.compile import cache_stats_since

        if self._commit_cache_baseline is None:
            raise RuntimeError(
                "ShardingAdvisor.retrace_report: no commit to audit"
            )
        delta = cache_stats_since(self._commit_cache_baseline)
        delta_causes = delta["miss_causes"]
        extra_misses = int(delta["misses"])
        expected = self._expected_retraces
        ok = (
            extra_misses <= expected["new_keys"]
            and sum(delta_causes.values()) <= expected["new_keys"]
            and all(cause in expected["causes"] for cause in delta_causes)
        )
        audit = {
            "extra_traces": int(delta["traces"]),
            "extra_misses": extra_misses,
            "miss_causes": delta_causes,
            "expected": dict(expected),
            "ok": bool(ok),
        }
        self._record(
            "audit",
            state_from=self.state,
            state_to=self.state,
            trigger=audit,
            rationale=(
                "trace-safety audit: cache delta since commit matches the "
                "ledgered expectation"
                if ok
                else "trace-safety audit FAILED: unexpected compile-cache "
                "traffic since sharding commit"
            ),
        )
        return audit

    def decision_ledger(self) -> List[Dict[str, Any]]:
        """Every decision this advisor took, oldest first — stable schema
        (``kind == "sharding_decision"``), safe to mutate."""
        import copy

        return copy.deepcopy(self._ledger)

    def export_ledger(
        self, path: Optional[str] = None, stream: Optional[Any] = None
    ) -> List[str]:
        """Write the ledger through the export front door: one JSONL line per
        decision, stamped with ``schema_version`` + process identity and
        parseable back via ``observability.parse_export_line`` — the same
        contract as ``SyncAutotuner.export_ledger``."""
        from torchmetrics_tpu.observability.export import JSONLinesExporter

        exporter = JSONLinesExporter(path=path, stream=stream)
        return [exporter.export(entry) for entry in self._ledger]

    def report(self) -> Dict[str, Any]:
        """The ``sharding`` block for the export front door."""
        return {
            "state": self.state,
            "counts": dict(self.counts),
            "decisions": len(self._ledger),
            "expected_retraces": dict(self._expected_retraces),
        }

    def _record(
        self,
        action: str,
        state_from: str,
        state_to: Optional[str] = None,
        targets: Optional[List[str]] = None,
        applied: Optional[bool] = None,
        trigger: Optional[Mapping[str, Any]] = None,
        rationale: str = "",
        alert: Optional[Any] = None,
        error: Optional[str] = None,
        expected_retraces: Optional[Mapping[str, Any]] = None,
    ) -> Dict[str, Any]:
        import copy

        entry: Dict[str, Any] = {
            "kind": SHARDING_LEDGER_KIND,
            "seq": self._seq,
            "action": action,
            "state_from": state_from,
            "state_to": self.state if state_to is None else state_to,
            "targets": list(targets or []),
            "applied": bool(applied) if applied is not None else None,
            "trigger": dict(trigger) if trigger else {},
            "rationale": rationale,
        }
        if alert is not None:
            entry["alert"] = alert.as_dict() if hasattr(alert, "as_dict") else dict(alert)
        if error is not None:
            entry["error"] = error
        if expected_retraces is not None:
            entry["expected_retraces"] = dict(expected_retraces)
        self._seq += 1
        self._ledger.append(entry)
        self._flight_record(entry)
        return copy.deepcopy(entry)

    def _flight_record(self, entry: Mapping[str, Any]) -> None:
        """Chrome-trace instant under the ``policy`` category, beside the
        autotuner's — one timeline shows both control loops."""
        from torchmetrics_tpu.observability import tracing

        if not tracing.active():
            return
        rec = tracing.recorder()
        if rec is None:  # pragma: no cover - active() already checked
            return
        rec.instant(
            f"sharding/{entry['action']}",
            "policy",
            seq=entry["seq"],
            state_from=entry["state_from"],
            state_to=entry["state_to"],
            targets=entry["targets"],
            applied=entry["applied"],
            rationale=entry["rationale"],
        )


# ---------------------------------------------------------------------------
# the front-door report
# ---------------------------------------------------------------------------


def memory_report(
    metrics: Optional[Iterable[Union[Any, Tuple[str, Any]]]] = None,
    n_devices: Optional[int] = None,
) -> Dict[str, Any]:
    """One ``kind: "memory_report"`` payload tying all three layers together,
    ready for ``observability.export`` (the JSONL line parses back through
    ``parse_export_line``; the Prometheus exporter renders the
    ``tm_tpu_memory_*`` / ``tm_tpu_cost_*`` families from it).

    Layout::

        {"schema": 1, "kind": "memory_report", "armed": bool,
         "memory": {
            "metrics": {label: memory-dict, ...},   # live watermark rows
            "executables": [...],                   # memory_timeline()
            "cost": {...},                          # cost_by_fingerprint()
            "advice": {...}}}                       # iff metrics given

    ``metrics`` (when given) additionally runs the :class:`ShardingAdvisor`
    over those instances.
    """
    rep = registry.report()
    mem_metrics = {
        label: row["memory"]
        for label, row in rep.get("metrics", {}).items()
        if isinstance(row.get("memory"), Mapping)
        and (row["memory"].get("installs") or row["memory"].get("snapshots"))
    }
    payload: Dict[str, Any] = {
        "schema": 1,
        "kind": "memory_report",
        "armed": memory_telemetry_enabled(),
        "enabled": registry.enabled(),
        "memory": {
            "metrics": mem_metrics,
            "executables": memory_timeline(),
            "cost": cost_by_fingerprint(),
        },
    }
    if metrics is not None:
        payload["memory"]["advice"] = ShardingAdvisor().advise(metrics, n_devices=n_devices)
    return payload


# the sizer is harmless to install eagerly (it only runs once armed), and
# installing it here means arming via the registry flag alone also works
registry.set_memory_sizer(state_memory_rows)

# honour TM_TPU_MEMORY_TELEMETRY=1 the way registry honours TM_TPU_TELEMETRY
if os.environ.get("TM_TPU_MEMORY_TELEMETRY", "").strip().lower() in (
    "1",
    "true",
    "on",
    "yes",
):  # pragma: no cover - env-driven path
    enable_memory_telemetry()
