"""Memory & cost observability plane: per-metric HBM attribution, compiled-
executable memory/cost analysis, and a report-only :class:`ShardingAdvisor`.

The sync planes (PRs 6-11) made *wire* bytes measurable; this module does the
same for *resident* bytes, in three attribution layers:

1. **Live state-HBM accounting** — every state install (the pytree rebound to
   ``metric._state`` by update/forward/restore) is sized per-leaf and folded
   into the telemetry registry as current/peak watermarks plus a
   donated-vs-copied install byte split.  Sizing is *sharded-aware*: a leaf's
   resident bytes are its per-shard bytes times its **addressable** device
   count (what this host's HBM actually holds), not its logical bytes — a
   replicated (2048, 2048) float32 on 8 local devices really occupies
   8 x 16 MiB.  The sizer reads only aval metadata (shape/dtype/sharding),
   never device buffers, so the armed path cannot retrace.
2. **Compiled-executable analysis** — while armed, every compile-cache entry
   in ``core/compile.py`` records ``compiled.memory_analysis()`` (argument /
   output / temp / generated-code bytes, plus peak HBM where the backend
   reports it) and ``cost_analysis()`` (FLOPs, bytes accessed), keyed by the
   same 12-hex config fingerprints as ``compile_timeline()``.  Surfaced via
   :func:`memory_timeline` / :func:`cost_by_fingerprint`; backends without
   analyses (CPU reports no peak) degrade to whatever fields exist, with
   ``available`` flagging rows where analysis failed entirely.
3. **Replication-waste attribution** — each psum-family state leaf is
   replicated across the mesh today, wasting ``leaf_bytes x (n_devices - 1)``
   of cluster HBM.  The :class:`ShardingAdvisor` ranks those leaves and
   quotes, per candidate, the granule-aware ring all-reduce bytes it pays now
   versus the reduce-scatter bytes it would pay sharded (arxiv 2004.13336's
   weight-update sharding applied to metric state) — the exact interface the
   ROADMAP item-1 sharding planner will consume.  Report-only: nothing here
   changes how state is placed.

Everything is double-gated: :func:`enable_memory_telemetry` arms the plane,
but nothing records until ``observability.enable()`` is also on (mirroring
the flight recorder).  Arming adds **zero retraces and zero cache entries**:
state sizing happens outside traced code, and executable analysis re-lowers
through jax's jaxpr cache (the traced body does not re-run; the one-off cost
is a second XLA compile per entry while armed).  Proven by the jaxpr
bit-identity and ``cache_stats`` delta tests in ``test_memory.py``.

Quick tour::

    from torchmetrics_tpu import observability as obs
    from torchmetrics_tpu.observability import memory

    obs.enable()
    memory.enable_memory_telemetry()      # or TM_TPU_MEMORY_TELEMETRY=1
    ...                                   # train; installs are sized live
    acc.telemetry.as_dict()["memory"]     # watermarks + per-leaf bytes
    memory.memory_timeline()              # per-entry executable analyses
    memory.cost_by_fingerprint()          # FLOPs/bytes by config fingerprint
    advice = memory.ShardingAdvisor().advise([fid, psnr])
    advice["candidates"][0]               # biggest replicated-waste leaf
    obs.export(memory.memory_report([fid, psnr]), fmt="jsonl")

A cheap, device-free example (the doctest tier-1 actually runs)::

    >>> from torchmetrics_tpu.classification import MulticlassConfusionMatrix
    >>> from torchmetrics_tpu.observability.memory import ShardingAdvisor
    >>> m = MulticlassConfusionMatrix(num_classes=64)
    >>> advice = ShardingAdvisor().advise([m], n_devices=8)
    >>> [c["leaf"] for c in advice["candidates"]]
    ['confmat']
    >>> advice["candidates"][0]["replicated_waste_bytes"] == 64 * 64 * 4 * 7
    True
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple, Union

import jax

from torchmetrics_tpu.core import compile as _compile
from torchmetrics_tpu.core.compile import cost_by_fingerprint, memory_timeline
from torchmetrics_tpu.observability import registry
from torchmetrics_tpu.utilities.benchmark import (
    RING_GRANULE_BYTES,
    _is_psum_shaped,
    reduce_scatter_bytes,
    ring_reduce_bytes,
)

__all__ = [
    "ShardingAdvisor",
    "cost_by_fingerprint",
    "disable_memory_telemetry",
    "enable_memory_telemetry",
    "leaf_resident_bytes",
    "memory_report",
    "memory_telemetry_enabled",
    "memory_timeline",
    "snapshot_metric",
    "state_memory_rows",
]

_log = logging.getLogger("torchmetrics_tpu.observability")


# ---------------------------------------------------------------------------
# layer 1: live state-HBM sizing
# ---------------------------------------------------------------------------


def leaf_resident_bytes(leaf: Any) -> Tuple[int, int]:
    """``(resident_bytes, logical_bytes)`` of one array-like leaf.

    Logical bytes are ``size x itemsize``.  Resident bytes are what this
    host's HBM holds: per-shard bytes times the sharding's **addressable**
    device count — so a fully replicated leaf on 8 local devices counts 8x
    its logical bytes, while a leaf sharded 8 ways counts exactly once.
    Falls back to logical bytes when the leaf has no sharding (tracers,
    numpy, scalars mid-trace).  Reads only metadata, never device buffers.
    """
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0, 0
    try:
        itemsize = int(dtype.itemsize)
    except AttributeError:
        import numpy as np

        itemsize = int(np.dtype(dtype).itemsize)
    logical = int(math.prod(shape)) * itemsize
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
            n_addressable = len(sharding.addressable_devices)
            return int(math.prod(shard_shape)) * itemsize * n_addressable, logical
        except Exception:  # tracers expose .sharding without a concrete mesh
            pass
    return logical, logical


def state_memory_rows(state: Any) -> Tuple[Dict[str, Dict[str, int]], int]:
    """Size a state pytree into ``({leaf_name: {"bytes", "logical_bytes"}},
    resident_total)`` — the sizer the registry calls on every install.

    Dict states (the ``Metric._state`` layout) keep their top-level names, so
    leaf rows line up with the reduction table; nested pytree leaves (sketch
    states) are summed under their top-level name.  Non-dict pytrees fall
    back to jax tree-path names.
    """
    if isinstance(state, Mapping):
        items: Iterable[Tuple[str, Any]] = state.items()
    else:
        items = [
            (jax.tree_util.keystr(path), leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]
        ]
    leaves: Dict[str, Dict[str, int]] = {}
    resident_total = 0
    for name, sub in items:
        resident = logical = 0
        for leaf in jax.tree.leaves(sub):
            r, l = leaf_resident_bytes(leaf)
            resident += r
            logical += l
        if resident or logical:
            leaves[str(name)] = {"bytes": resident, "logical_bytes": logical}
            resident_total += resident
    return leaves, resident_total


def snapshot_metric(metric: Any) -> None:
    """Record ``metric``'s *current* state residency into the registry right
    now, without waiting for the next install — useful when arming after the
    metric already accumulated state.  Counted as a snapshot, not an install.
    Same double gate as install accounting; a no-op while unarmed."""
    state = getattr(metric, "_state", None)
    if state:
        registry.record_state_snapshot(metric, state)


# ---------------------------------------------------------------------------
# arming (the second half of the double gate)
# ---------------------------------------------------------------------------


def enable_memory_telemetry() -> None:
    """Arm the memory plane: live install sizing in the registry plus
    per-entry executable analysis capture in the compile cache.

    Nothing records until ``observability.enable()`` is also on.  Arming
    changes no cache key and adds no retrace: sizing reads aval metadata
    outside traced code, and executable analysis re-lowers each entry through
    jax's shared jaxpr cache (the Python body does not re-run; the cost is
    one extra XLA compile per new entry while armed)."""
    registry.set_memory_sizer(state_memory_rows)
    registry.set_memory_armed(True)
    _compile.set_analysis_capture(True)


def disable_memory_telemetry() -> None:
    """Disarm the memory plane.  Recorded watermarks and analysis rows are
    kept (clear them with ``reset_telemetry()`` / ``clear_compile_cache()``);
    new installs and new cache entries stop being sized."""
    registry.set_memory_armed(False)
    _compile.set_analysis_capture(False)


def memory_telemetry_enabled() -> bool:
    """True while the memory plane is armed (the registry gate; executable
    capture is armed and disarmed in lockstep)."""
    return registry.memory_armed()


# ---------------------------------------------------------------------------
# layer 3: replication-waste attribution
# ---------------------------------------------------------------------------


class ShardingAdvisor:
    """Report-only advisor ranking the state leaves worth sharding.

    For each psum-family leaf (the reductions ``core.reductions.sync_leaf``
    lowers to a ring all-reduce) of each metric, computes:

    * ``replicated_waste_bytes`` — ``leaf_bytes x (n_devices - 1)``, the
      cluster HBM spent on redundant replicas today;
    * ``ring_allreduce_bytes_per_chip`` — granule-aware per-chip wire bytes
      one combine pays while replicated (``utilities.benchmark``'s model);
    * ``reduce_scatter_bytes_per_chip`` — what the same combine would pay
      with the leaf reduce-scattered (exactly the scatter half of the ring);
    * ``projected_wire_savings_bytes_per_chip`` — the difference.

    Leaf bytes come from the live registry rows when the memory plane has
    recorded them (``source: "registry"`` — this is how the bench reproduces
    BENCH_r05's FID+PSNR 33,570,840-byte figure from live attribution), else
    from the metric's state pytree directly (``source: "state"``).  Gather-
    family leaves (cat/reservoir/structural sketches) are excluded: they are
    not replicated-by-sum, so sharding them is a different problem.

    Report-only by construction: the advisor never touches placement.  Its
    output dict is the interface the ROADMAP item-1 cross-replica sharding
    planner will consume, and what ``memory_report()`` exports under
    ``memory.advice``.
    """

    def __init__(
        self,
        n_devices: Optional[int] = None,
        granule: int = RING_GRANULE_BYTES,
        min_leaf_bytes: int = 1 << 20,
    ) -> None:
        self.n_devices = n_devices
        self.granule = int(granule)
        #: leaves at or above this size make the ``recommended`` short list;
        #: below it the granule floor erodes the reduce-scatter win and the
        #: HBM recovered is noise
        self.min_leaf_bytes = int(min_leaf_bytes)

    @staticmethod
    def _label_for(metric: Any) -> str:
        t = registry.telemetry_for(metric, create=False)
        return t.label if t is not None else type(metric).__name__

    @staticmethod
    def _live_leaves_for(metric: Any) -> Optional[Dict[str, Dict[str, int]]]:
        t = registry.telemetry_for(metric, create=False)
        if t is None:
            return None
        leaves = t.memory.get("leaves")
        return dict(leaves) if leaves else None

    def advise(
        self,
        metrics: Iterable[Union[Any, Tuple[str, Any]]],
        n_devices: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rank every psum-family leaf of ``metrics`` by replicated waste.

        ``metrics`` holds metric instances or ``(label, metric)`` pairs;
        unlabelled metrics take their telemetry label (or class name).
        ``n_devices`` defaults to the advisor's, then ``jax.device_count()``.
        """
        n = int(n_devices or self.n_devices or jax.device_count())
        candidates: List[Dict[str, Any]] = []
        total_psum = 0
        for item in metrics:
            if isinstance(item, tuple) and len(item) == 2 and isinstance(item[0], str):
                label, metric = item
            else:
                label, metric = self._label_for(item), item
            reductions = getattr(metric, "_reductions", None) or {}
            state = getattr(metric, "_state", None) or {}
            live = self._live_leaves_for(metric)
            for name, reduce in sorted(reductions.items()):
                if name not in state or not _is_psum_shaped(reduce):
                    continue
                row = (live or {}).get(name)
                if row and row.get("logical_bytes"):
                    nbytes = int(row["logical_bytes"])
                    source = "registry"
                else:
                    nbytes = sum(
                        leaf_resident_bytes(leaf)[1] for leaf in jax.tree.leaves(state[name])
                    )
                    source = "state"
                if nbytes <= 0:
                    continue
                ring = ring_reduce_bytes(nbytes, n, self.granule)
                scatter = reduce_scatter_bytes(nbytes, n, self.granule)
                candidates.append(
                    {
                        "metric": label,
                        "leaf": name,
                        "bytes": nbytes,
                        "source": source,
                        "replicated_waste_bytes": nbytes * (n - 1),
                        "ring_allreduce_bytes_per_chip": ring,
                        "reduce_scatter_bytes_per_chip": scatter,
                        "projected_wire_savings_bytes_per_chip": ring - scatter,
                        "worth_sharding": nbytes >= self.min_leaf_bytes,
                    }
                )
                total_psum += nbytes
        candidates.sort(key=lambda c: (-c["replicated_waste_bytes"], c["metric"], c["leaf"]))
        return {
            "kind": "sharding_advice",
            "n_devices": n,
            "granule_bytes": self.granule,
            "min_leaf_bytes": self.min_leaf_bytes,
            "total_psum_state_bytes": total_psum,
            "total_replicated_waste_bytes": total_psum * (n - 1),
            "total_ring_allreduce_bytes_per_chip": sum(
                c["ring_allreduce_bytes_per_chip"] for c in candidates
            ),
            "total_reduce_scatter_bytes_per_chip": sum(
                c["reduce_scatter_bytes_per_chip"] for c in candidates
            ),
            "total_projected_wire_savings_bytes_per_chip": sum(
                c["projected_wire_savings_bytes_per_chip"] for c in candidates
            ),
            "candidates": candidates,
            "recommended": [
                f"{c['metric']}/{c['leaf']}" for c in candidates if c["worth_sharding"]
            ],
            "note": (
                "report-only: states stay replicated until the cross-replica "
                "sharding planner lands; candidates ranked by replicated HBM waste"
            ),
        }


# ---------------------------------------------------------------------------
# the front-door report
# ---------------------------------------------------------------------------


def memory_report(
    metrics: Optional[Iterable[Union[Any, Tuple[str, Any]]]] = None,
    n_devices: Optional[int] = None,
) -> Dict[str, Any]:
    """One ``kind: "memory_report"`` payload tying all three layers together,
    ready for ``observability.export`` (the JSONL line parses back through
    ``parse_export_line``; the Prometheus exporter renders the
    ``tm_tpu_memory_*`` / ``tm_tpu_cost_*`` families from it).

    Layout::

        {"schema": 1, "kind": "memory_report", "armed": bool,
         "memory": {
            "metrics": {label: memory-dict, ...},   # live watermark rows
            "executables": [...],                   # memory_timeline()
            "cost": {...},                          # cost_by_fingerprint()
            "advice": {...}}}                       # iff metrics given

    ``metrics`` (when given) additionally runs the :class:`ShardingAdvisor`
    over those instances.
    """
    rep = registry.report()
    mem_metrics = {
        label: row["memory"]
        for label, row in rep.get("metrics", {}).items()
        if isinstance(row.get("memory"), Mapping)
        and (row["memory"].get("installs") or row["memory"].get("snapshots"))
    }
    payload: Dict[str, Any] = {
        "schema": 1,
        "kind": "memory_report",
        "armed": memory_telemetry_enabled(),
        "enabled": registry.enabled(),
        "memory": {
            "metrics": mem_metrics,
            "executables": memory_timeline(),
            "cost": cost_by_fingerprint(),
        },
    }
    if metrics is not None:
        payload["memory"]["advice"] = ShardingAdvisor().advise(metrics, n_devices=n_devices)
    return payload


# the sizer is harmless to install eagerly (it only runs once armed), and
# installing it here means arming via the registry flag alone also works
registry.set_memory_sizer(state_memory_rows)

# honour TM_TPU_MEMORY_TELEMETRY=1 the way registry honours TM_TPU_TELEMETRY
if os.environ.get("TM_TPU_MEMORY_TELEMETRY", "").strip().lower() in (
    "1",
    "true",
    "on",
    "yes",
):  # pragma: no cover - env-driven path
    enable_memory_telemetry()
