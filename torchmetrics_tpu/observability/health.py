"""Streaming metric-health monitors: deterministic, step-indexed alerting
over computed metric values.

The registry answers *what the library did*; this module watches *what the
metrics said*.  A :class:`HealthMonitor` holds per-series streaming rules —

* :class:`BoundRule` — value escaped ``[min_value, max_value]``,
* :class:`DriftRule` — EMA z-score drift: the value sits ``z_threshold``
  deviations from its exponentially-weighted mean/variance,
* :class:`NonFiniteRule` — NaN/Inf observation rate above ``max_rate``,
* :class:`MemoryBudgetRule` — live metric-state HBM (the armed memory
  plane's ``current_bytes`` watermark) above a configured byte budget,
* :class:`AccuracyBudgetRule` — composed worst-case error bound (the armed
  accuracy plane's attested ``bound``, or a shadow audit's observed error)
  above the declared error budget,
* :class:`CatStateBudgetRule` — cat-state bytes (the armed gather plane's
  ``hwm_bytes`` high-watermark, or a ``project_gather_bytes`` pod-scale
  projection) above a configured byte budget,
* :class:`StalenessRule` — a watched series not observed for more than
  ``max_stale_steps`` steps (checked on :meth:`HealthMonitor.advance`),

— and routes every violation as a severity-leveled :class:`Alert` to the
configured sinks: :class:`LoggingAlertSink` (library logger),
:class:`JSONLAlertSink` (one line per alert through the PR 3
``JSONLinesExporter`` — each line carries ``schema_version`` and the process
identity and parses back with ``export.parse_export_line``), and
:class:`CallbackAlertSink`.

Everything is **step-indexed and deterministic**: the monitor never reads a
wall clock or RNG (TMT006-clean by construction), so the same value stream
at the same steps produces the same alerts on every host and every rerun —
replayable from a JSONL value log.  Nothing here enters a traced graph; the
monitor consumes already-computed host values, so arming it can never change
a cache key or add a retrace.

Quick tour::

    from torchmetrics_tpu.observability import health

    mon = health.HealthMonitor(sinks=[health.LoggingAlertSink()])
    mon.watch("val/accuracy", health.BoundRule(min_value=0.0, max_value=1.0),
              health.DriftRule(z_threshold=4.0), health.StalenessRule(100))
    for step in range(steps):
        ...
        mon.observe("val/accuracy", float(acc.compute()), step=step)
        mon.advance(step)
    mon.export(fmt="jsonl", stream=log)   # the report, via the front door
"""

from __future__ import annotations

import logging
import math
from collections import deque
from typing import Any, Callable, Dict, IO, List, Mapping, Optional, Tuple

__all__ = [
    "AccuracyBudgetRule",
    "Alert",
    "AlertSink",
    "BoundRule",
    "CallbackAlertSink",
    "CatStateBudgetRule",
    "DriftRule",
    "HealthMonitor",
    "HealthRule",
    "JSONLAlertSink",
    "LoggingAlertSink",
    "MemoryBudgetRule",
    "NonFiniteRule",
    "QuarantineRule",
    "SEVERITIES",
    "StalenessRule",
]

_log = logging.getLogger("torchmetrics_tpu.observability")

#: alert severities, mildest first; sinks filter with ``min_severity``
SEVERITIES = ("info", "warning", "critical")


def _severity_rank(severity: str) -> int:
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        raise ValueError(
            f"unknown alert severity {severity!r}; expected one of {SEVERITIES}"
        ) from None


def _json_safe(value: Optional[float]) -> Any:
    """Floats for JSON lines: non-finite values become strings (strict JSON
    has no NaN/Infinity literals)."""
    if value is None:
        return None
    v = float(value)
    return v if math.isfinite(v) else repr(v)


class Alert:
    """One rule violation: which series, which rule, at which step."""

    __slots__ = ("series", "rule", "severity", "step", "value", "message", "details")

    def __init__(
        self,
        series: str,
        rule: str,
        severity: str,
        step: int,
        value: Optional[float],
        message: str,
        details: Optional[Mapping[str, Any]] = None,
    ) -> None:
        _severity_rank(severity)  # validates
        self.series = series
        self.rule = rule
        self.severity = severity
        self.step = int(step)
        self.value = value
        self.message = message
        self.details = dict(details) if details else {}

    def as_dict(self) -> Dict[str, Any]:
        return {
            "kind": "health_alert",
            "series": self.series,
            "rule": self.rule,
            "severity": self.severity,
            "step": self.step,
            "value": _json_safe(self.value),
            "message": self.message,
            "details": {k: _json_safe(v) if isinstance(v, float) else v
                        for k, v in self.details.items()},
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"Alert({self.severity} {self.series}/{self.rule} @step {self.step}: {self.message})"


# -------------------------------------------------------------------- sinks
class AlertSink:
    """Interface: subclasses implement :meth:`write`; :meth:`emit` applies
    the ``min_severity`` filter shared by every sink."""

    def __init__(self, min_severity: str = "info") -> None:
        self._min_rank = _severity_rank(min_severity)

    def emit(self, alert: Alert) -> None:
        if _severity_rank(alert.severity) >= self._min_rank:
            self.write(alert)

    def write(self, alert: Alert) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class LoggingAlertSink(AlertSink):
    """Route alerts through the library logger (silent until the application
    configures handlers), mapping severity to the logging level."""

    _LEVELS = {"info": logging.INFO, "warning": logging.WARNING, "critical": logging.ERROR}

    def __init__(
        self, logger: Optional[logging.Logger] = None, min_severity: str = "info"
    ) -> None:
        super().__init__(min_severity)
        self.logger = logger if logger is not None else _log

    def write(self, alert: Alert) -> None:
        self.logger.log(
            self._LEVELS[alert.severity],
            "health[%s] %s/%s at step %d: %s",
            alert.severity,
            alert.series,
            alert.rule,
            alert.step,
            alert.message,
            extra={"health_alert": alert.as_dict()},
        )


class JSONLAlertSink(AlertSink):
    """One JSON line per alert through the PR 3 ``JSONLinesExporter`` — the
    existing export front door, so every line carries ``schema_version`` plus
    the process identity and parses back via ``export.parse_export_line``."""

    def __init__(
        self,
        path: Optional[str] = None,
        stream: Optional[IO[str]] = None,
        min_severity: str = "info",
    ) -> None:
        super().__init__(min_severity)
        from torchmetrics_tpu.observability.export import JSONLinesExporter

        self._exporter = JSONLinesExporter(path=path, stream=stream)

    def write(self, alert: Alert) -> None:
        self._exporter.export(alert.as_dict())


class CallbackAlertSink(AlertSink):
    """Hand each alert to ``fn(alert)`` — pagers, test hooks, custom fanout."""

    def __init__(self, fn: Callable[[Alert], None], min_severity: str = "info") -> None:
        super().__init__(min_severity)
        self._fn = fn

    def write(self, alert: Alert) -> None:
        self._fn(alert)


# -------------------------------------------------------------------- rules
class HealthRule:
    """Interface for streaming per-series rules.

    One rule instance may watch many series: state is keyed by series name.
    :meth:`check` runs on every observation and returns an :class:`Alert` or
    ``None``; :meth:`sweep` runs on :meth:`HealthMonitor.advance` for rules
    (staleness) that fire on the *absence* of observations.
    """

    name = "rule"

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        return None

    def sweep(self, series: str, step: int) -> Optional[Alert]:
        return None


class BoundRule(HealthRule):
    """Value escaped ``[min_value, max_value]`` (either side optional)."""

    name = "bound"

    def __init__(
        self,
        min_value: Optional[float] = None,
        max_value: Optional[float] = None,
        severity: str = "critical",
    ) -> None:
        if min_value is None and max_value is None:
            raise ValueError("BoundRule needs min_value and/or max_value")
        if min_value is not None and max_value is not None and min_value > max_value:
            raise ValueError(f"BoundRule: min_value {min_value} > max_value {max_value}")
        self.min_value = min_value
        self.max_value = max_value
        self.severity = severity

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None  # NonFiniteRule's jurisdiction
        if self.min_value is not None and value < self.min_value:
            side, bound = "below min", self.min_value
        elif self.max_value is not None and value > self.max_value:
            side, bound = "above max", self.max_value
        else:
            return None
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"value {value!r} {side} bound {bound!r}",
            {"min_value": self.min_value, "max_value": self.max_value},
        )


class DriftRule(HealthRule):
    """EMA z-score drift: alert when a value lands ``z_threshold`` deviations
    from its exponentially-weighted mean.

    Mean and variance update with the standard EW recurrences
    (``mean += alpha * delta``; ``var = (1-alpha) * (var + alpha * delta^2)``)
    *after* the check, so a spike is judged against the history that preceded
    it.  The first ``warmup`` finite observations only train the estimate.
    """

    name = "drift"

    def __init__(
        self,
        z_threshold: float = 4.0,
        alpha: float = 0.1,
        warmup: int = 10,
        severity: str = "warning",
    ) -> None:
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"DriftRule alpha must be in (0, 1], got {alpha}")
        if z_threshold <= 0.0:
            raise ValueError(f"DriftRule z_threshold must be > 0, got {z_threshold}")
        self.z_threshold = float(z_threshold)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.severity = severity
        # series -> (n_finite, ew_mean, ew_var)
        self._series_state: Dict[str, Tuple[int, float, float]] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None
        n, mean, var = self._series_state.get(series, (0, 0.0, 0.0))
        alert = None
        if n >= self.warmup and var > 0.0:
            z = (value - mean) / math.sqrt(var)
            if abs(z) >= self.z_threshold:
                alert = Alert(
                    series,
                    self.name,
                    self.severity,
                    step,
                    value,
                    f"z-score {z:.2f} beyond ±{self.z_threshold:g} "
                    f"(ema mean {mean:.6g}, ema std {math.sqrt(var):.3g})",
                    {"z": z, "ema_mean": mean, "ema_var": var},
                )
        if n == 0:
            mean, var = value, 0.0
        else:
            delta = value - mean
            mean += self.alpha * delta
            var = (1.0 - self.alpha) * (var + self.alpha * delta * delta)
        self._series_state[series] = (n + 1, mean, var)
        return alert


class NonFiniteRule(HealthRule):
    """NaN/Inf observation rate above ``max_rate`` (default 0: every
    non-finite value alerts)."""

    name = "nonfinite"

    def __init__(self, max_rate: float = 0.0, severity: str = "critical") -> None:
        if not (0.0 <= max_rate < 1.0):
            raise ValueError(f"NonFiniteRule max_rate must be in [0, 1), got {max_rate}")
        self.max_rate = float(max_rate)
        self.severity = severity
        # series -> (total, nonfinite)
        self._series_state: Dict[str, Tuple[int, int]] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        total, bad = self._series_state.get(series, (0, 0))
        total += 1
        finite = math.isfinite(value)
        if not finite:
            bad += 1
        self._series_state[series] = (total, bad)
        rate = bad / total
        if finite or rate <= self.max_rate:
            return None
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"non-finite value ({bad}/{total} observations, "
            f"rate {rate:.3f} > {self.max_rate:g})",
            {"nonfinite": bad, "total": total, "rate": rate},
        )


class StalenessRule(HealthRule):
    """Series not observed for more than ``max_stale_steps`` steps.

    Fires once per staleness episode on :meth:`HealthMonitor.advance` (the
    latch clears when the series is observed again), so a stalled producer
    does not page on every step.
    """

    name = "staleness"

    def __init__(self, max_stale_steps: int, severity: str = "warning") -> None:
        if max_stale_steps < 1:
            raise ValueError(f"StalenessRule max_stale_steps must be >= 1, got {max_stale_steps}")
        self.max_stale_steps = int(max_stale_steps)
        self.severity = severity
        self._last_step: Dict[str, int] = {}
        self._latched: Dict[str, bool] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        self._last_step[series] = step
        self._latched[series] = False
        return None

    def sweep(self, series: str, step: int) -> Optional[Alert]:
        last = self._last_step.get(series)
        if last is None:
            # never observed: measure staleness from the first sweep instead
            self._last_step[series] = last = step
            return None
        stale = step - last
        if stale <= self.max_stale_steps or self._latched.get(series):
            return None
        self._latched[series] = True
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            None,
            f"no observation for {stale} steps (limit {self.max_stale_steps})",
            {"stale_steps": stale, "last_step": last},
        )


class MemoryBudgetRule(HealthRule):
    """Live metric-state HBM above ``budget_bytes``.

    Feed it the ``current_bytes`` watermark the armed memory plane records
    (``metric.telemetry.as_dict()["memory"]["current_bytes"]``, or the
    ``memory_report()`` rows) as the observed value.  Fires once per breach
    episode — the latch clears the first time the series drops back to or
    under budget — so a metric that plateaus above budget pages once, not
    every step.
    """

    name = "memory_budget"

    def __init__(self, budget_bytes: int, severity: str = "warning") -> None:
        if budget_bytes <= 0:
            raise ValueError(f"MemoryBudgetRule budget_bytes must be > 0, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.severity = severity
        self._latched: Dict[str, bool] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None  # NonFiniteRule's jurisdiction
        if value <= self.budget_bytes:
            self._latched[series] = False
            return None
        if self._latched.get(series):
            return None
        self._latched[series] = True
        over = value - self.budget_bytes
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"live state HBM {int(value)} bytes exceeds budget "
            f"{self.budget_bytes} by {int(over)}",
            {"budget_bytes": self.budget_bytes, "over_bytes": over},
        )


class AccuracyBudgetRule(HealthRule):
    """Composed worst-case error bound above the declared error budget.

    Feed it the composed predicted bound the armed accuracy plane attests
    (``attestation["bound"]``, or a :class:`~torchmetrics_tpu.observability.
    accuracy.ShadowAuditor`'s observed error) with ``budget`` set to the
    declared budget it must stay under (``approx_error``,
    ``SyncPolicy.error_budget``, or their sum for stacked sources).  Fires
    once per breach episode — the latch clears the first time the series
    drops back to or under budget — same latch discipline as
    :class:`MemoryBudgetRule`.
    """

    name = "accuracy_budget"

    def __init__(self, budget: float, severity: str = "critical") -> None:
        if not (budget > 0.0) or not math.isfinite(budget):
            raise ValueError(f"AccuracyBudgetRule budget must be a finite float > 0, got {budget}")
        self.budget = float(budget)
        self.severity = severity
        self._latched: Dict[str, bool] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None  # NonFiniteRule's jurisdiction
        if value <= self.budget:
            self._latched[series] = False
            return None
        if self._latched.get(series):
            return None
        self._latched[series] = True
        over = value - self.budget
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"error bound {value:.6g} exceeds declared budget "
            f"{self.budget:.6g} by {over:.3g}",
            {"budget": self.budget, "over": over},
        )


class CatStateBudgetRule(HealthRule):
    """Cat-state size (or its pod-scale projection) above ``budget_bytes``.

    Feed it the gather plane's live attribution — the ``hwm_bytes``
    high-watermark from ``metric.telemetry.as_dict()["gathers"]``, or a
    ``project_gather_bytes(n_chips)`` per-chip projection — as the observed
    value.  Cat states grow linearly with steps *and* with chip count
    (BENCH_r05: mAP at 5,402,880 bytes/chip/step on 64 chips), so this is
    the rule that pages before an eval loop gathers itself out of HBM or
    DCN headroom.  Fires once per breach episode — the latch clears the
    first time the series drops back to or under budget (a reset/retire
    shrinking the cat) — same latch discipline as :class:`MemoryBudgetRule`,
    and fleet-mergeable the same way (per-series state keys the latch).
    """

    name = "cat_state_budget"

    def __init__(self, budget_bytes: int, severity: str = "warning") -> None:
        if budget_bytes <= 0:
            raise ValueError(
                f"CatStateBudgetRule budget_bytes must be > 0, got {budget_bytes}"
            )
        self.budget_bytes = int(budget_bytes)
        self.severity = severity
        self._latched: Dict[str, bool] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None  # NonFiniteRule's jurisdiction
        if value <= self.budget_bytes:
            self._latched[series] = False
            return None
        if self._latched.get(series):
            return None
        self._latched[series] = True
        over = value - self.budget_bytes
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"cat-state bytes {int(value)} exceed budget "
            f"{self.budget_bytes} by {int(over)}",
            {"budget_bytes": self.budget_bytes, "over_bytes": over},
        )


class QuarantineRule(HealthRule):
    """Replicas quarantined out of the sync quorum.

    Feed it the quarantined-replica count (``resilience.quarantine`` does
    this automatically through ``attach_monitor``).  Fires on every
    *escalation* — each time the count rises past its previous alerted
    level — and the latch rewinds when the count falls back, so a fleet
    that loses one replica pages once, a fleet that keeps losing replicas
    pages on each loss, and a recovered fleet can page again on the next
    episode.  ``max_quarantined`` tolerates a baseline (default 0: any
    quarantined replica alerts).
    """

    name = "quarantine"

    def __init__(self, max_quarantined: int = 0, severity: str = "critical") -> None:
        if max_quarantined < 0:
            raise ValueError(
                f"QuarantineRule max_quarantined must be >= 0, got {max_quarantined}"
            )
        self.max_quarantined = int(max_quarantined)
        self.severity = severity
        self._alerted: Dict[str, int] = {}

    def check(self, series: str, step: int, value: float) -> Optional[Alert]:
        if not math.isfinite(value):
            return None  # NonFiniteRule's jurisdiction
        count = int(value)
        prev = self._alerted.get(series, 0)
        if count <= self.max_quarantined or count <= prev:
            if count < prev:
                self._alerted[series] = count
            return None
        self._alerted[series] = count
        return Alert(
            series,
            self.name,
            self.severity,
            step,
            value,
            f"{count} replica(s) quarantined out of the sync quorum "
            f"(tolerated {self.max_quarantined}); evaluation continues degraded",
            {"quarantined": count, "max_quarantined": self.max_quarantined},
        )


# ------------------------------------------------------------------ monitor
class HealthMonitor:
    """Streaming health monitor over computed metric values.

    ``watch`` registers a series with its rules; ``observe`` feeds one value
    at one step (values must already be host floats — computing a metric is
    the caller's business, the monitor never triggers device work);
    ``advance`` runs the staleness sweep.  Alerts fan out to every sink and
    land in a bounded ring (``max_alerts``) for :meth:`report`.
    """

    def __init__(
        self,
        sinks: Optional[List[AlertSink]] = None,
        max_alerts: int = 1024,
    ) -> None:
        if max_alerts < 1:
            raise ValueError(f"HealthMonitor max_alerts must be >= 1, got {max_alerts}")
        self.sinks: List[AlertSink] = list(sinks) if sinks else []
        self._rules: Dict[str, List[HealthRule]] = {}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._alerts: "deque[Alert]" = deque(maxlen=max_alerts)
        self._counts: Dict[str, int] = {sev: 0 for sev in SEVERITIES}
        self._dropped = 0
        self._step: Optional[int] = None

    # ------------------------------------------------------------- wiring
    def add_sink(self, sink: AlertSink) -> "HealthMonitor":
        self.sinks.append(sink)
        return self

    def watch(self, series: str, *rules: HealthRule) -> "HealthMonitor":
        """Register ``series`` with ``rules`` (appending on repeat calls)."""
        if not rules:
            raise ValueError(f"watch({series!r}) needs at least one rule")
        self._rules.setdefault(series, []).extend(rules)
        self._last.setdefault(
            series, {"value": None, "step": None, "observations": 0}
        )
        return self

    @property
    def series(self) -> Tuple[str, ...]:
        return tuple(sorted(self._rules))

    # ------------------------------------------------------------ feeding
    def observe(self, series: str, value: Any, step: int) -> List[Alert]:
        """Feed one observation; returns the alerts it raised (also routed
        to the sinks and the ring)."""
        v = float(value)
        step = int(step)
        slot = self._last.setdefault(
            series, {"value": None, "step": None, "observations": 0}
        )
        slot["value"] = v
        slot["step"] = step
        slot["observations"] += 1
        if self._step is None or step > self._step:
            self._step = step
        raised: List[Alert] = []
        for rule in self._rules.get(series, ()):
            alert = rule.check(series, step, v)
            if alert is not None:
                raised.append(alert)
        for alert in raised:
            self._record(alert)
        return raised

    def advance(self, step: int) -> List[Alert]:
        """Mark the stream position and run the staleness sweep."""
        step = int(step)
        if self._step is None or step > self._step:
            self._step = step
        raised: List[Alert] = []
        for series, rules in sorted(self._rules.items()):
            for rule in rules:
                alert = rule.sweep(series, step)
                if alert is not None:
                    raised.append(alert)
        for alert in raised:
            self._record(alert)
        return raised

    def _record(self, alert: Alert) -> None:
        if len(self._alerts) == self._alerts.maxlen:
            self._dropped += 1
        self._alerts.append(alert)
        self._counts[alert.severity] = self._counts.get(alert.severity, 0) + 1
        for sink in self.sinks:
            try:
                sink.emit(alert)
            except Exception:  # a broken pager must not break the step loop
                _log.debug("health alert sink %r failed", sink, exc_info=True)

    # ------------------------------------------------------------ reading
    def alerts(self, severity: Optional[str] = None) -> List[Alert]:
        """The retained alerts, oldest first (optionally one severity)."""
        if severity is None:
            return list(self._alerts)
        _severity_rank(severity)  # validates
        return [a for a in self._alerts if a.severity == severity]

    @property
    def alert_counts(self) -> Dict[str, int]:
        return dict(self._counts)

    def report(self) -> Dict[str, Any]:
        """Structured snapshot: per-series state plus alert totals.  Shaped
        for the export front door — ``export(monitor.report(), fmt=...)``
        renders JSONL/log directly and Prometheus via the ``health`` block."""
        series: Dict[str, Any] = {}
        for name in sorted(set(self._rules) | set(self._last)):
            slot = self._last.get(name, {"value": None, "step": None, "observations": 0})
            sev_counts = {sev: 0 for sev in SEVERITIES}
            for a in self._alerts:
                if a.series == name:
                    sev_counts[a.severity] += 1
            series[name] = {
                "last_value": _json_safe(slot["value"]),
                "last_step": slot["step"],
                "observations": slot["observations"],
                "rules": [r.name for r in self._rules.get(name, ())],
                "alerts": sev_counts,
            }
        return {
            "schema": 1,
            "kind": "health",
            "step": self._step,
            "health": {
                "series": series,
                "alerts": dict(self._counts),
                "alerts_total": sum(self._counts.values()),
                "alerts_dropped": self._dropped,
                "recent": [a.as_dict() for a in list(self._alerts)[-16:]],
            },
        }

    def export(self, fmt: str = "jsonl", **kwargs: Any) -> Any:
        """Export :meth:`report` through ``observability.export.export``."""
        from torchmetrics_tpu.observability.export import export as _export

        return _export(self.report(), fmt=fmt, **kwargs)
