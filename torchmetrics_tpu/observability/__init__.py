"""Observability layer: per-metric telemetry, profiler attribution, exporters.

Off by default.  Turn it on with :func:`enable` (or ``TM_TPU_TELEMETRY=1``)
and every metric starts counting its updates/computes/forwards/resets,
cross-device syncs (with modelled per-chip byte traffic), donated-vs-copied
state installs, non-finite events, snapshot/restore events, and
per-entrypoint compile-cache hits/misses/retraces — plus fixed-bucket timing
histograms of the host-side ``update``/``compute``/sync boundaries.  While
enabled, compiled metric work is also visible in TPU profiler traces under
``tm_tpu/<MetricClass>/<entrypoint>`` scopes.

Quick tour::

    from torchmetrics_tpu import observability as obs

    obs.enable()
    ...  # train
    acc.telemetry.as_dict()              # one metric's counters/spans
    obs.report()                          # everything, as one dict
    obs.export(fmt="prometheus")          # or "jsonl" / "log"

    with obs.observe("eval") as window:   # scoped diff around a phase
        ...
    window.diff["global"]["counters"]["updates"]

    obs.fleet_report()                    # pod-global merged report (identity
                                          # on one process; skew + straggler
                                          # attribution on many)
    obs.HealthMonitor(...)                # streaming metric-health alerting

    obs.enable_memory_telemetry()         # arm the memory & cost plane:
    obs.memory_report([fid, psnr])        # HBM watermarks, executable
                                          # analyses, ShardingAdvisor advice

    obs.enable_accuracy_telemetry()       # arm the accuracy plane: every
    obs.accuracy_report([auroc])          # compute() attests its composed
                                          # error bound + provenance; shadow-
                                          # exact audits check observed error

    obs.enable_gather_telemetry()         # arm the gather plane: cat-state
    obs.gather_report()                   # growth attribution, pod-scale
                                          # projections, GatherAdvisor advice

The disabled fast path is a no-op: no compile-cache observer is registered,
recording helpers return after one flag check, and nothing here touches
cache keys — so telemetry can never cause a retrace.
"""

from torchmetrics_tpu.observability import accuracy, fleet, gathers, health, memory, tracing
from torchmetrics_tpu.observability.accuracy import (
    ShadowAuditor,
    ValueAttestation,
    accuracy_report,
    accuracy_telemetry_enabled,
    attest,
    disable_accuracy_telemetry,
    enable_accuracy_telemetry,
)
from torchmetrics_tpu.observability.export import (
    ChromeTraceExporter,
    Exporter,
    JSONLinesExporter,
    LoggingExporter,
    PrometheusExporter,
    SCHEMA_VERSION,
    TraceJSONLinesExporter,
    export,
    parse_export_line,
    parse_stats,
)
from torchmetrics_tpu.observability.gathers import (
    GatherAdvisor,
    disable_gather_telemetry,
    enable_gather_telemetry,
    gather_report,
    gather_telemetry_enabled,
    project_gather_bytes,
)
from torchmetrics_tpu.observability.fleet import (
    FleetView,
    fleet_report,
    gather_reports,
    process_count,
    process_index,
)
from torchmetrics_tpu.observability.health import (
    AccuracyBudgetRule,
    Alert,
    AlertSink,
    BoundRule,
    CallbackAlertSink,
    CatStateBudgetRule,
    DriftRule,
    HealthMonitor,
    HealthRule,
    JSONLAlertSink,
    LoggingAlertSink,
    MemoryBudgetRule,
    NonFiniteRule,
    QuarantineRule,
    SEVERITIES,
    StalenessRule,
)
from torchmetrics_tpu.observability.memory import (
    ShardingAdvisor,
    cost_by_fingerprint,
    disable_memory_telemetry,
    enable_memory_telemetry,
    memory_report,
    memory_telemetry_enabled,
    memory_timeline,
)
from torchmetrics_tpu.observability.tracing import FlightRecorder, TraceEvent
from torchmetrics_tpu.observability.registry import (
    COUNTER_NAMES,
    MetricTelemetry,
    ObservationWindow,
    SPAN_BUCKETS_US,
    aggregate_telemetry,
    diff_report,
    disable,
    enable,
    enabled,
    observe,
    report,
    reset_telemetry,
    telemetry_for,
)

__all__ = [
    "AccuracyBudgetRule",
    "Alert",
    "AlertSink",
    "BoundRule",
    "COUNTER_NAMES",
    "CallbackAlertSink",
    "CatStateBudgetRule",
    "ChromeTraceExporter",
    "DriftRule",
    "Exporter",
    "FleetView",
    "FlightRecorder",
    "GatherAdvisor",
    "HealthMonitor",
    "HealthRule",
    "JSONLAlertSink",
    "JSONLinesExporter",
    "LoggingAlertSink",
    "LoggingExporter",
    "MemoryBudgetRule",
    "MetricTelemetry",
    "NonFiniteRule",
    "ObservationWindow",
    "PrometheusExporter",
    "QuarantineRule",
    "SCHEMA_VERSION",
    "SEVERITIES",
    "SPAN_BUCKETS_US",
    "ShadowAuditor",
    "ShardingAdvisor",
    "StalenessRule",
    "TraceEvent",
    "TraceJSONLinesExporter",
    "ValueAttestation",
    "accuracy",
    "accuracy_report",
    "accuracy_telemetry_enabled",
    "aggregate_telemetry",
    "attest",
    "cost_by_fingerprint",
    "diff_report",
    "disable",
    "disable_accuracy_telemetry",
    "disable_gather_telemetry",
    "disable_memory_telemetry",
    "enable",
    "enable_accuracy_telemetry",
    "enable_gather_telemetry",
    "enable_memory_telemetry",
    "enabled",
    "export",
    "fleet",
    "fleet_report",
    "gather_report",
    "gather_reports",
    "gather_telemetry_enabled",
    "gathers",
    "health",
    "memory",
    "memory_report",
    "memory_telemetry_enabled",
    "memory_timeline",
    "observe",
    "parse_export_line",
    "parse_stats",
    "process_count",
    "process_index",
    "project_gather_bytes",
    "report",
    "reset_telemetry",
    "telemetry_for",
    "tracing",
]

# honour TM_TPU_TELEMETRY=1: registry seeds the flag at import; finish the
# job by subscribing to compile-cache events
if enabled():  # pragma: no cover - env-driven path
    enable()
