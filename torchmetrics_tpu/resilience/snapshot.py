"""Preemption-safe metric snapshots: a versioned checkpoint format with
validate-before-install restore.

Training jobs on preemptible pods die mid-epoch; what kills the *run* is not
the preemption but a silently corrupted resume — a checkpoint written by a
different metric config, a truncated leaf, a shape that only explodes three
steps later inside a compiled update.  The snapshot format here is
self-describing so every restore is validated **before any state leaf is
touched**:

``snapshot(metric) ->``::

    {
        "schema_version": 1,
        "kind": "metric",
        "class": "torchmetrics_tpu.classification...BinaryAccuracy",
        "spec": {leaf: {"kind": "array", "shape": [...], "dtype": "..."}
                       | {"kind": "list", ...}
                       | {"kind": "sharded", "axis": ..., "n_shards": ...,
                          "shapes": [...], "logical_shape": [...], "dtype": "..."}},
        "state": {leaf: np.ndarray | [np.ndarray, ...]},   # host numpy pytree
    }

A leaf carrying a ``state_sharding`` spec whose live value is genuinely
device-sharded (``add_state(..., state_sharding="sharded")`` after a
reduce-scatter sync) is stored as its **per-shard** payloads, in shard-axis
order — each shard is a separate array in the payload list, so the durable
store's per-array CRC walk covers every shard independently.  Restore
reassembles the shards (concatenate along the shard axis, slice padding back
to the recorded ``logical_shape``) into a plain mesh-agnostic logical array
before validation, which is what makes elastic 8→4→8 restores bit-identical:
the installed state never depends on the producing mesh size.

``snapshot(collection)`` wraps one metric snapshot per member plus the
compute-group partition, so restore re-establishes state aliasing exactly
(group members share ONE pytree again, ``_state_shared`` marked — the PR 1
donation contract survives the round-trip).

``restore`` (and the rewired ``Metric.load_state_dict`` /
``load_state_pytree`` paths, which share :func:`validate_state_leaf` /
:func:`validate_state_pytree`) raises a structured
:class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError` naming the
offending leaf on any mismatch.  Payloads are plain ``dict``/``list``/numpy
— picklable, ``np.savez``-able, orbax-friendly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.core.guards import RESERVED_STATE_KEYS
from torchmetrics_tpu.core.metric import Metric, State
from torchmetrics_tpu.core.reductions import Reduce
from torchmetrics_tpu.utilities.exceptions import StateRestoreError

__all__ = [
    "SCHEMA_VERSION",
    "class_fingerprint",
    "restore",
    "snapshot",
    "validate_state_leaf",
    "validate_state_pytree",
    "with_snapshot_context",
]

SCHEMA_VERSION = 1

_N = "_n"
_NONFINITE = "_nonfinite"


def class_fingerprint(obj: Any) -> str:
    """Stable identity of the snapshotted class: ``module.qualname``."""
    return f"{type(obj).__module__}.{type(obj).__qualname__}"


def _is_growable(metric: Metric, name: str) -> bool:
    """True for leaves whose leading dim may legitimately differ from the
    default (cat/None-reduce concat states grow with the data)."""
    reduce = metric._reductions.get(name)
    return reduce in (Reduce.CAT, Reduce.NONE) or (callable(reduce) and not isinstance(reduce, Reduce))


# ------------------------------------------------------------------ validate
def validate_state_leaf(metric: Metric, name: str, value: Any) -> Any:
    """Validate ONE state leaf against the metric's spec; return the
    installable (jnp) leaf.  Raises :class:`StateRestoreError` naming the
    leaf on any kind/shape/dtype mismatch — never touches metric state."""
    if name in RESERVED_STATE_KEYS:
        arr = np.asarray(value)
        if arr.size != 1 or not np.issubdtype(arr.dtype, np.integer):
            raise StateRestoreError(
                f"Reserved counter leaf {name!r} must be an integer scalar; got "
                f"shape {tuple(arr.shape)} dtype {arr.dtype}.",
                leaf=name,
                reason="counter",
            )
        return jnp.asarray(arr.reshape(()), jnp.int32)

    if name not in metric._defaults:
        raise StateRestoreError(
            f"Leaf {name!r} is not a registered state of {type(metric).__name__} "
            f"(known: {sorted(metric._defaults)}).",
            leaf=name,
            reason="unknown-leaf",
        )
    default = metric._defaults[name]

    if isinstance(default, tuple):  # list ("cat") state
        if not isinstance(value, (list, tuple)):
            raise StateRestoreError(
                f"List-state leaf {name!r} of {type(metric).__name__} expects a sequence of "
                f"arrays; got {type(value).__name__}.",
                leaf=name,
                reason="kind",
            )
        items = []
        dtype = None
        for j, item in enumerate(value):
            arr = np.asarray(item)
            if dtype is None:
                dtype = arr.dtype
            elif arr.dtype != dtype:
                raise StateRestoreError(
                    f"List-state leaf {name!r} item {j} has dtype {arr.dtype}, but item 0 "
                    f"has {dtype}: a snapshot's list items must share one dtype.",
                    leaf=name,
                    reason="dtype",
                )
            items.append(jnp.asarray(arr))
        return tuple(items)

    if isinstance(value, (list, tuple)):
        raise StateRestoreError(
            f"Tensor-state leaf {name!r} of {type(metric).__name__} expects an array; got a "
            f"sequence of {len(value)} item(s).",
            leaf=name,
            reason="kind",
        )
    # a jnp leaf passes through untouched: checks read only shape/dtype
    # metadata, so a device-sharded value keeps its placement (a numpy
    # round-trip would gather every shard to host and re-replicate)
    arr = value if isinstance(value, jnp.ndarray) else np.asarray(value)
    if np.dtype(arr.dtype) != np.asarray(default).dtype:
        raise StateRestoreError(
            f"State leaf {name!r} of {type(metric).__name__} has dtype {arr.dtype}, "
            f"expected {np.asarray(default).dtype}.",
            leaf=name,
            reason="dtype",
        )
    if _is_growable(metric, name):
        if arr.ndim != np.asarray(default).ndim:
            raise StateRestoreError(
                f"Growable state leaf {name!r} of {type(metric).__name__} has rank {arr.ndim}, "
                f"expected {np.asarray(default).ndim}.",
                leaf=name,
                reason="shape",
            )
    elif tuple(arr.shape) != tuple(np.asarray(default).shape):
        sliced = _slice_sharding_padding(metric, name, arr)
        if sliced is None:
            raise StateRestoreError(
                f"State leaf {name!r} of {type(metric).__name__} has shape {tuple(arr.shape)}, "
                f"expected {tuple(np.asarray(default).shape)}.",
                leaf=name,
                reason="shape",
            )
        arr = sliced
    return jnp.asarray(arr)


def _slice_sharding_padding(metric: Metric, name: str, arr: Any) -> Optional[Any]:
    """A sharded leaf's live value may carry divisibility padding (identity
    zeros) on its shard axis; accept it by slicing back to the logical dim.
    Returns ``None`` unless ``arr`` matches the default everywhere except an
    oversized shard axis on a leaf with an installed ``state_sharding``."""
    spec = (getattr(metric, "_state_shardings", None) or {}).get(name)
    if spec is None:
        return None
    default_shape = tuple(np.asarray(metric._defaults[name]).shape)
    axis = spec.axis
    if arr.ndim != len(default_shape) or axis >= arr.ndim:
        return None
    if arr.shape[axis] < default_shape[axis]:
        return None
    if any(
        arr.shape[d] != default_shape[d] for d in range(arr.ndim) if d != axis
    ):
        return None
    index = [slice(None)] * arr.ndim
    index[axis] = slice(0, default_shape[axis])
    return arr[tuple(index)]


def validate_state_pytree(metric: Metric, state: Mapping[str, Any]) -> State:
    """Validate a FULL state pytree against the metric's spec; return the
    installable state dict (fresh jnp leaves).

    Checks structure first (missing / unknown leaves), then every leaf's
    kind/shape/dtype via :func:`validate_state_leaf`.  The reserved ``_n``
    counter is preserved from the current state when absent; the
    ``_nonfinite`` counter is synthesized/dropped to match the metric's
    ``nan_strategy``.  Raises :class:`StateRestoreError` before anything is
    installed.
    """
    if not isinstance(state, Mapping):
        raise StateRestoreError(
            f"Expected a state mapping for {type(metric).__name__}, got {type(state).__name__}.",
            reason="structure",
        )
    provided = {k for k in state if k not in RESERVED_STATE_KEYS}
    expected = set(metric._defaults)
    missing = sorted(expected - provided)
    if missing:
        raise StateRestoreError(
            f"State for {type(metric).__name__} is missing leaf {missing[0]!r} "
            f"(all missing: {missing}).",
            leaf=missing[0],
            reason="missing-leaf",
        )
    unknown = sorted(provided - expected)
    if unknown:
        raise StateRestoreError(
            f"State for {type(metric).__name__} contains unknown leaf {unknown[0]!r} "
            f"(all unknown: {unknown}; known: {sorted(expected)}).",
            leaf=unknown[0],
            reason="unknown-leaf",
        )
    out: State = {}
    for name in metric._defaults:
        out[name] = validate_state_leaf(metric, name, state[name])
    if _N in state:
        out[_N] = validate_state_leaf(metric, _N, state[_N])
    else:  # functional states without the counter keep the current count
        out[_N] = metric._state.get(_N, jnp.zeros((), jnp.int32))
    if metric._guard_strategy in ("warn", "error"):
        if _NONFINITE in state:
            out[_NONFINITE] = validate_state_leaf(metric, _NONFINITE, state[_NONFINITE])
        else:
            from torchmetrics_tpu.core.guards import count_nonfinite

            out[_NONFINITE] = count_nonfinite(out)
    return out


# ------------------------------------------------------------------ snapshot
def _leaf_spec(leaf: Any) -> Dict[str, Any]:
    if isinstance(leaf, (tuple, list)):
        arrs = [np.asarray(x) for x in leaf]
        return {
            "kind": "list",
            "length": len(arrs),
            "shapes": [list(a.shape) for a in arrs],
            "dtype": str(arrs[0].dtype) if arrs else None,
        }
    arr = np.asarray(leaf)
    return {"kind": "array", "shape": list(arr.shape), "dtype": str(arr.dtype)}


def _shard_payload(leaf: Any, axis: int) -> Optional[List[np.ndarray]]:
    """Per-shard numpy payloads of a genuinely device-sharded array, in
    shard-axis order; ``None`` when the leaf holds one (replicated) shard or
    is not a device array.  Shards are deduplicated by their index window
    (replicas of the same window are one payload)."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards:
        return None
    by_window: Dict[Tuple, Any] = {}
    for shard in shards:
        index = tuple(
            (s.start if s.start is not None else 0, s.stop) for s in shard.index
        )
        by_window.setdefault(index, shard)
    if len(by_window) <= 1:
        return None
    ordered = sorted(by_window.items(), key=lambda kv: kv[0][axis][0])
    return [np.asarray(shard.data) for _, shard in ordered]


def _metric_snapshot(metric: Metric) -> Dict[str, Any]:
    from torchmetrics_tpu.observability import registry as _telemetry

    _telemetry.count(metric, "snapshots")
    state = metric.state_pytree()
    shardings = getattr(metric, "_state_shardings", None) or {}
    payload: Dict[str, Any] = {}
    spec: Dict[str, Any] = {}
    for name, leaf in state.items():
        shard_spec = shardings.get(name)
        parts = (
            _shard_payload(leaf, shard_spec.axis) if shard_spec is not None else None
        )
        if parts is not None:
            spec[name] = {
                "kind": "sharded",
                "axis": int(shard_spec.axis),
                "n_shards": len(parts),
                "shapes": [list(p.shape) for p in parts],
                "logical_shape": list(np.asarray(metric._defaults[name]).shape),
                "dtype": str(parts[0].dtype),
            }
            payload[name] = parts
            continue
        spec[name] = _leaf_spec(leaf)
        if isinstance(leaf, (tuple, list)):
            payload[name] = [np.asarray(x) for x in leaf]
        else:
            payload[name] = np.asarray(leaf)
    return {
        "schema_version": SCHEMA_VERSION,
        "kind": "metric",
        "class": class_fingerprint(metric),
        "spec": spec,
        "state": payload,
    }


def snapshot(obj: Any, *, mesh_shape: Optional[Sequence[int]] = None) -> Dict[str, Any]:
    """Versioned host-numpy snapshot of a metric or collection.

    The result is self-describing (schema version, class fingerprint,
    per-leaf shape/dtype spec) so :func:`restore` can reject corruption or a
    config mismatch with a structured error instead of poisoning state.
    Plain dict/list/numpy payload: picklable and ``np.savez``/orbax-friendly.

    ``mesh_shape`` optionally records the device mesh the state was produced
    on (e.g. ``(8,)``).  Restore never *requires* it — replicated metric
    state is mesh-agnostic — but when present it rides along in the header
    so restore diagnostics (and the elastic-restore path) can name the
    producing mesh instead of failing with only a bad leaf name.
    """
    from torchmetrics_tpu.collections import MetricCollection

    if isinstance(obj, MetricCollection):
        groups: Optional[List[List[str]]] = None
        if obj._groups and obj._groups_checked:
            groups = [list(members) for members in obj._groups.values()]
        snap = {
            "schema_version": SCHEMA_VERSION,
            "kind": "collection",
            "class": class_fingerprint(obj),
            "groups": groups,
            "metrics": {key: _metric_snapshot(m) for key, m in obj.items(keep_base=True)},
        }
    elif isinstance(obj, Metric):
        snap = _metric_snapshot(obj)
    else:
        raise TypeError(f"snapshot() takes a Metric or MetricCollection, got {type(obj).__name__}")
    if mesh_shape is not None:
        snap["mesh"] = [int(d) for d in mesh_shape]
    return snap


def with_snapshot_context(
    err: StateRestoreError,
    snap: Any,
    *,
    generation: Optional[int] = None,
) -> StateRestoreError:
    """Re-raiseable copy of ``err`` stamped with the snapshot's identity.

    Restore failures deep in leaf validation only know the offending leaf;
    the caller holding the snapshot header (and, for durable restores, the
    generation id) uses this to produce the full diagnostic: schema version,
    producing mesh shape, and generation, both as message text and as
    structured attributes on the error.
    """
    schema = err.schema_version
    mesh = err.mesh_shape
    if isinstance(snap, Mapping):
        if schema is None:
            schema = snap.get("schema_version")
        if mesh is None:
            mesh = snap.get("mesh")
    gen = err.generation if err.generation is not None else generation
    parts = []
    if schema is not None:
        parts.append(f"schema_version={schema!r}")
    if mesh is not None:
        parts.append(f"mesh={tuple(mesh)!r}")
    if gen is not None:
        parts.append(f"generation={gen}")
    message = str(err)
    # idempotent: a previously-stamped context block is replaced, not stacked
    idx = message.rfind(" [snapshot ")
    if idx != -1 and message.endswith("]"):
        message = message[:idx]
    if parts:
        message = f"{message} [snapshot {' '.join(parts)}]"
    out = StateRestoreError(
        message,
        leaf=err.leaf,
        reason=err.reason,
        schema_version=schema,
        mesh_shape=tuple(mesh) if mesh is not None else None,
        generation=gen,
    )
    return out


# ------------------------------------------------------------------- restore
def _check_header(snap: Any, expect_kind: str, target: Any, strict_class: bool) -> None:
    if not isinstance(snap, Mapping):
        raise StateRestoreError(
            f"Snapshot must be a mapping, got {type(snap).__name__}.", reason="structure"
        )
    version = snap.get("schema_version")
    if version != SCHEMA_VERSION:
        raise StateRestoreError(
            f"Snapshot schema_version {version!r} is not supported (this build reads "
            f"version {SCHEMA_VERSION}).",
            reason="schema-version",
        )
    kind = snap.get("kind")
    if kind != expect_kind:
        raise StateRestoreError(
            f"Snapshot kind {kind!r} cannot restore into a {type(target).__name__} "
            f"(expected kind {expect_kind!r}).",
            reason="kind",
        )
    if strict_class and snap.get("class") != class_fingerprint(target):
        raise StateRestoreError(
            f"Snapshot was taken from class {snap.get('class')!r} but is being restored "
            f"into {class_fingerprint(target)!r}; pass strict_class=False to override.",
            reason="class",
        )


def _check_payload_matches_spec(snap: Mapping[str, Any]) -> None:
    """Detect corruption: the recorded per-leaf spec must match the payload."""
    spec, payload = snap.get("spec"), snap.get("state")
    if not isinstance(spec, Mapping) or not isinstance(payload, Mapping):
        raise StateRestoreError(
            "Snapshot is missing its 'spec'/'state' sections.", reason="structure"
        )
    for name in spec:
        if name not in payload:
            raise StateRestoreError(
                f"Snapshot spec lists leaf {name!r} but the payload does not contain it "
                "(truncated or corrupted snapshot).",
                leaf=name,
                reason="corrupt",
            )
    for name, leaf in payload.items():
        entry = spec.get(name)
        if entry is None:
            raise StateRestoreError(
                f"Snapshot payload contains leaf {name!r} with no spec entry "
                "(corrupted snapshot).",
                leaf=name,
                reason="corrupt",
            )
        if entry.get("kind") == "sharded":
            _check_sharded_payload(name, entry, leaf)
            continue
        actual = _leaf_spec(leaf)
        if entry.get("kind") != actual["kind"]:
            raise StateRestoreError(
                f"Snapshot leaf {name!r} payload kind {actual['kind']!r} does not match its "
                f"recorded spec kind {entry.get('kind')!r} (corrupted snapshot).",
                leaf=name,
                reason="corrupt",
            )
        if actual["kind"] == "array":
            if list(entry.get("shape", [])) != actual["shape"] or entry.get("dtype") != actual["dtype"]:
                raise StateRestoreError(
                    f"Snapshot leaf {name!r} payload (shape {actual['shape']}, dtype "
                    f"{actual['dtype']}) does not match its recorded spec (shape "
                    f"{entry.get('shape')}, dtype {entry.get('dtype')}) — corrupted snapshot.",
                    leaf=name,
                    reason="corrupt",
                )
        elif entry.get("length") != actual["length"] or entry.get("shapes") != actual["shapes"]:
            raise StateRestoreError(
                f"Snapshot list leaf {name!r} payload does not match its recorded item "
                "shapes (corrupted snapshot).",
                leaf=name,
                reason="corrupt",
            )


def _check_sharded_payload(name: str, entry: Mapping[str, Any], leaf: Any) -> None:
    """Spec/payload agreement for one ``kind: "sharded"`` leaf: a sequence of
    exactly ``n_shards`` arrays whose per-shard shapes and shared dtype match
    what the snapshot recorded."""
    if not isinstance(leaf, (list, tuple)):
        raise StateRestoreError(
            f"Snapshot sharded leaf {name!r} payload must be a sequence of per-shard "
            f"arrays; got {type(leaf).__name__} (corrupted snapshot).",
            leaf=name,
            reason="corrupt",
        )
    parts = [np.asarray(p) for p in leaf]
    if len(parts) != int(entry.get("n_shards", -1)):
        raise StateRestoreError(
            f"Snapshot sharded leaf {name!r} payload holds {len(parts)} shard(s) but its "
            f"spec records {entry.get('n_shards')} (corrupted snapshot).",
            leaf=name,
            reason="corrupt",
        )
    if [list(p.shape) for p in parts] != list(entry.get("shapes", [])) or any(
        str(p.dtype) != entry.get("dtype") for p in parts
    ):
        raise StateRestoreError(
            f"Snapshot sharded leaf {name!r} per-shard shapes/dtype do not match its "
            "recorded spec (corrupted snapshot).",
            leaf=name,
            reason="corrupt",
        )


def _reassemble_sharded(name: str, entry: Mapping[str, Any], parts: Sequence[Any]) -> np.ndarray:
    """Concatenate per-shard payloads along the shard axis and slice any
    divisibility padding back off, yielding the mesh-agnostic logical array.
    Mesh-size independence is the point: 8 shards from an 8-device run and
    4 shards from a 4-device run reassemble to the identical logical value."""
    axis = int(entry.get("axis", 0))
    full = np.concatenate([np.asarray(p) for p in parts], axis=axis)
    logical = entry.get("logical_shape")
    if logical is not None and full.shape[axis] > int(logical[axis]):
        index = [slice(None)] * full.ndim
        index[axis] = slice(0, int(logical[axis]))
        full = full[tuple(index)]
    return full


def _restore_metric(metric: Metric, snap: Mapping[str, Any], strict_class: bool) -> State:
    """Validate a metric snapshot fully; return the installable state."""
    _check_header(snap, "metric", metric, strict_class)
    _check_payload_matches_spec(snap)
    state: Dict[str, Any] = dict(snap["state"])
    spec = snap.get("spec")
    if isinstance(spec, Mapping):
        for name, entry in spec.items():
            if isinstance(entry, Mapping) and entry.get("kind") == "sharded":
                state[name] = _reassemble_sharded(name, entry, state[name])
    return validate_state_pytree(metric, state)


def _install(metric: Metric, state: State) -> None:
    # the restore boundary lives on the Metric itself — one sanctioned place
    # where restored buffers land and the post-restore invariants are reset
    metric._install_restored_state(state)


def restore(obj: Any, snap: Mapping[str, Any], strict_class: bool = True) -> None:
    """Validate-then-install a snapshot into a metric or collection.

    Validation is all-or-nothing: every leaf of every member is checked
    (structure, shapes, dtypes, class fingerprint, spec/payload agreement)
    before ANY state is installed, so a failed restore leaves the target
    untouched.  For collections the snapshot's compute-group partition is
    re-established: members of a group share their leader's restored pytree
    and are re-marked as aliased (``_state_shared``) so compiled updates
    keep honoring the no-donate-aliased-state contract.

    Any :class:`StateRestoreError` raised here is stamped with the
    snapshot's identity (schema version, producing mesh shape when recorded)
    via :func:`with_snapshot_context` so the diagnostic names *which*
    checkpoint failed, not just the bad leaf.
    """
    try:
        _restore_validated(obj, snap, strict_class)
    except StateRestoreError as err:
        raise with_snapshot_context(err, snap) from None


def _restore_validated(obj: Any, snap: Mapping[str, Any], strict_class: bool) -> None:
    from torchmetrics_tpu.collections import MetricCollection

    if isinstance(obj, MetricCollection):
        _check_header(snap, "collection", obj, strict_class)
        members_snap = snap.get("metrics")
        if not isinstance(members_snap, Mapping):
            raise StateRestoreError(
                "Collection snapshot is missing its 'metrics' section.", reason="structure"
            )
        keys = set(obj.keys(keep_base=True))
        missing = sorted(keys - set(members_snap))
        if missing:
            raise StateRestoreError(
                f"Collection snapshot is missing member {missing[0]!r} (all missing: {missing}).",
                leaf=missing[0],
                reason="missing-leaf",
            )
        unknown = sorted(set(members_snap) - keys)
        if unknown:
            raise StateRestoreError(
                f"Collection snapshot contains unknown member {unknown[0]!r} "
                f"(all unknown: {unknown}).",
                leaf=unknown[0],
                reason="unknown-leaf",
            )
        groups = snap.get("groups")
        if groups is not None:
            flat = [name for members in groups for name in members]
            bad = sorted(set(flat) - keys)
            if bad:
                raise StateRestoreError(
                    f"Snapshot compute group names {bad} are not members of this collection.",
                    leaf=bad[0],
                    reason="groups",
                )
            if len(flat) != len(set(flat)):
                raise StateRestoreError(
                    "Snapshot compute groups assign a metric to more than one group.",
                    reason="groups",
                )
        # two-phase: validate everything, then install everything
        staged = {key: _restore_metric(obj[key], members_snap[key], strict_class) for key in keys}
        for key in keys:
            _install(obj[key], staged[key])
        if groups is not None:
            obj._groups = {i: list(members) for i, members in enumerate(groups)}
            obj._groups_checked = True
            for members in groups:
                leader_state = obj[members[0]]._state
                for name in members[1:]:
                    obj[name]._state = leader_state  # tmt: ignore[TMT007] -- compute-group re-aliasing on restore: collection state lifecycle
                obj._mark_shared(list(members))
        return
    if isinstance(obj, Metric):
        _install(obj, _restore_metric(obj, snap, strict_class))
        return
    raise TypeError(f"restore() takes a Metric or MetricCollection, got {type(obj).__name__}")
