"""Cross-replica divergence detection.

Replicas that are supposed to hold *identical* metric state — every device's
copy of a replicated post-sync state, every host's copy of the global
accumulator — can silently drift apart: an uneven restore across hosts, a
replica that lost a step to preemption, a flipped bit.  Every downstream
aggregate then looks plausible and is wrong.

Instead of shipping full states around to compare, each replica's state is
reduced to one cheap order-sensitive uint32 checksum per leaf
(``core/guards.py``) and the digests are compared with a single
``pmin``/``pmax`` collective over the mesh axis
(``core.compile.compiled_divergence_check`` — for any total order, min
equals max iff every replica agrees).  A mismatch raises
:class:`~torchmetrics_tpu.utilities.exceptions.ReplicaDivergenceError`
naming the divergent leaves and the replicas that disagree with the
majority.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchmetrics_tpu.core.guards import leaf_digest
from torchmetrics_tpu.utilities.exceptions import ReplicaDivergenceError

__all__ = ["replica_digest_table", "verify_replica_consistency"]

State = Dict[str, Any]


def _check_same_structure(states: Sequence[State]) -> List[str]:
    names = sorted(states[0])
    for i, st in enumerate(states[1:], start=1):
        if sorted(st) != names:
            diff = sorted(set(names).symmetric_difference(st))
            raise ReplicaDivergenceError(
                f"replica state structures disagree: replica 0 holds leaves {names}, "
                f"replica {i} holds {sorted(st)} (differing: {diff}).",
                leaves=diff,
                replicas=[i],
            )
    return names


def replica_digest_table(states: Sequence[State]) -> "np.ndarray":
    """``(n_replicas, n_leaves)`` uint32 checksum matrix of per-replica
    states (leaves in sorted-name order).  Raises
    :class:`ReplicaDivergenceError` if the replicas' leaf *names* already
    disagree."""
    names = _check_same_structure(states)
    table = np.zeros((len(states), len(names)), np.uint32)
    for i, st in enumerate(states):
        for j, name in enumerate(names):
            table[i, j] = np.asarray(leaf_digest(st[name]), np.uint32)
    return table


def _replica_views(state: State, mesh: Mesh) -> Optional[List[State]]:
    """Per-device views of a replicated state pytree, or ``None`` when no
    leaf actually lives replicated on the mesh devices (nothing to compare).

    Only leaves whose every addressable shard carries the *full* value (true
    replication) are expanded per device; host leaves and genuinely sharded
    leaves are passed through as one shared object, which digests identically
    on every replica and therefore can never raise a false alarm.
    """
    devices = list(mesh.devices.flat)
    views: List[State] = [dict() for _ in devices]
    comparable = False
    for name, leaf in state.items():
        per_dev = None
        if isinstance(leaf, jax.Array) and not isinstance(leaf, jax.core.Tracer):
            try:
                shards = leaf.addressable_shards
            except Exception:
                shards = []
            full = {
                s.device: s.data for s in shards if tuple(s.data.shape) == tuple(leaf.shape)
            }
            if all(d in full for d in devices) and len(devices) > 1:
                per_dev = full
        if per_dev is not None:
            comparable = True
            for i, d in enumerate(devices):
                views[i][name] = per_dev[d]
        else:
            for i in range(len(devices)):
                views[i][name] = leaf
    return views if comparable else None


def verify_replica_consistency(
    metric: Any,
    mesh: Optional[Mesh] = None,
    states: Optional[Sequence[State]] = None,
    state: Optional[State] = None,
    axis_name: str = "data",
) -> None:
    """Verify that replicas holding supposedly-identical metric state agree.

    Two modes:

    * ``states=[state_0, ..., state_{n-1}]`` — explicit per-replica pytrees
      (e.g. each host's copy of the global accumulator after a restore).
    * otherwise — ``state`` (default: ``metric.state_pytree()``) is treated
      as a mesh-replicated pytree and each device's copy is checked.  Leaves
      that are not replicated on the mesh are skipped; with nothing
      replicated the check trivially passes.

    Each replica's state reduces to one uint32 checksum per leaf; when
    ``mesh`` is a 1-D mesh matching the replica count, the compare runs as a
    single in-graph ``pmin``/``pmax`` collective over ``axis_name``
    (cached in the unified compile registry), otherwise it runs on host.
    Disagreement raises :class:`ReplicaDivergenceError` naming the divergent
    leaves and the minority replicas.
    """
    if states is None:
        if mesh is None:
            raise ValueError("verify_replica_consistency needs `mesh` (or explicit `states`)")
        src = state if state is not None else metric.state_pytree()
        views = _replica_views(src, mesh)
        if views is None:
            return  # nothing replicated on this mesh — single source of truth
        states = views
    states = list(states)
    if len(states) < 2:
        return
    table = replica_digest_table(states)
    names = sorted(states[0])
    if not names:
        return

    agree: np.ndarray
    if (
        mesh is not None
        and int(mesh.devices.size) == len(states)
        and axis_name in mesh.shape
        and int(mesh.shape[axis_name]) == len(states)
    ):
        from torchmetrics_tpu.core.compile import compiled_divergence_check

        fn = compiled_divergence_check(mesh, axis_name, len(names), owner=metric)
        sharded = jax.device_put(table, NamedSharding(mesh, P(axis_name)))
        agree = np.asarray(fn(sharded))
    else:
        agree = (table == table[0]).all(axis=0)

    if bool(np.all(agree)):
        return
    bad_leaves = [names[j] for j in range(len(names)) if not agree[j]]
    bad_replicas: List[int] = []
    for j, name in enumerate(names):
        if agree[j]:
            continue
        col = table[:, j]
        vals, counts = np.unique(col, return_counts=True)
        majority = vals[int(np.argmax(counts))]
        bad_replicas.extend(int(i) for i in np.nonzero(col != majority)[0])
    bad_replicas = sorted(set(bad_replicas))
    raise ReplicaDivergenceError(
        f"metric state diverged across {len(states)} replicas: leaves {bad_leaves} do not "
        f"agree (replicas {bad_replicas} differ from the majority). The replicas were "
        "expected to hold identical state — check for an uneven restore or a replica "
        "that lost/duplicated an update step.",
        leaves=bad_leaves,
        replicas=bad_replicas,
    )
