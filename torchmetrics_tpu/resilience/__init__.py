"""Resilience layer: preemption-safe snapshots, validated restore, and
cross-replica divergence detection.

The three failure modes that kill long metric runs on preemptible pods —
preemption mid-epoch, silently corrupted restores, and replica state drift —
each get a first-class tool here:

* :func:`snapshot` / :func:`restore` — versioned, self-describing host-numpy
  checkpoints, validated leaf-by-leaf *before* any state is installed
  (``StateRestoreError`` names the offending leaf).
* :func:`verify_replica_consistency` — cheap per-leaf checksums compared
  with one ``pmin``/``pmax`` collective over the mesh axis
  (``ReplicaDivergenceError`` names the divergent leaves and replicas).
* :mod:`torchmetrics_tpu.resilience.faults` — deterministic fault injection
  (kill/restore, snapshot corruption, single-replica perturbation) for tests.

The jit-fused non-finite guards (``Metric(nan_strategy=...)``) live in
``core/guards.py`` so the core can apply them without importing this package.
"""

from torchmetrics_tpu.resilience.divergence import (
    replica_digest_table,
    verify_replica_consistency,
)
from torchmetrics_tpu.resilience.faults import (
    CORRUPTION_MODES,
    corrupt_snapshot,
    perturb_replica,
    run_with_preemption,
)
from torchmetrics_tpu.resilience.snapshot import (
    SCHEMA_VERSION,
    class_fingerprint,
    restore,
    snapshot,
    validate_state_leaf,
    validate_state_pytree,
)
from torchmetrics_tpu.utilities.exceptions import (
    NonFiniteStateError,
    ReplicaDivergenceError,
    StateRestoreError,
)

__all__ = [
    "CORRUPTION_MODES",
    "NonFiniteStateError",
    "ReplicaDivergenceError",
    "SCHEMA_VERSION",
    "StateRestoreError",
    "class_fingerprint",
    "corrupt_snapshot",
    "perturb_replica",
    "replica_digest_table",
    "restore",
    "run_with_preemption",
    "snapshot",
    "validate_state_leaf",
    "validate_state_pytree",
    "verify_replica_consistency",
]
