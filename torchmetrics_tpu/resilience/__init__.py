"""Resilience layer: preemption-safe snapshots, durable elastic
checkpointing, degraded-mode (quarantine) evaluation, and cross-replica
divergence detection.

The failure modes that kill long metric runs on preemptible pods each get a
first-class tool here:

* :func:`snapshot` / :func:`restore` — versioned, self-describing host-numpy
  checkpoints, validated leaf-by-leaf *before* any state is installed
  (``StateRestoreError`` names the offending leaf, schema version, producing
  mesh and — for durable restores — generation id).
* :class:`DurableSnapshotStore` — generational on-disk persistence with
  write-ahead manifests, per-leaf checksums, atomic commit renames, retrying
  I/O under a :class:`RetryPolicy`, skip-back past corrupt generations, and
  double-buffered async saves off the step path.
* :func:`elastic_restore` — resume a snapshot taken on an N-device mesh onto
  M devices; mid-window per-device carries are re-bucketed exactly via the
  metric's own ``merge_states`` (no sample lost, none double-counted).
* :func:`quarantine` + ``on_divergence="quarantine"`` — degraded-mode
  evaluation: divergent replicas are masked out of subsequent syncs by an
  in-graph weight, a ``QuarantineRule`` health alert fires, and ``compute``
  reports the surviving quorum instead of crashing the fleet.
* :func:`verify_replica_consistency` — cheap per-leaf checksums compared
  with one ``pmin``/``pmax`` collective over the mesh axis
  (``ReplicaDivergenceError`` names the divergent leaves and replicas).
* :mod:`torchmetrics_tpu.resilience.faults` — deterministic fault injection
  (kill/restore, snapshot corruption, torn writes, ENOSPC, crash-before-
  commit, transient flakes, stale executable envelopes, host loss
  mid-gather) for tests and drills.

The same durable substrate (``StorageBackend`` + ``RetryPolicy`` +
write-ahead crc manifests, shared through ``build_wire_manifest`` /
``parse_wire_manifest`` / ``verify_wire_payload``) also carries AOT-compiled
*executables* across restarts — see
:mod:`torchmetrics_tpu.core.warmstart`.

The jit-fused non-finite guards (``Metric(nan_strategy=...)``) live in
``core/guards.py`` so the core can apply them without importing this package.
"""

from torchmetrics_tpu.resilience.divergence import (
    replica_digest_table,
    verify_replica_consistency,
)
from torchmetrics_tpu.resilience.durable import (
    DurableSnapshotStore,
    LocalFSBackend,
    PendingSave,
    RetryPolicy,
    StorageBackend,
    build_wire_manifest,
    parse_wire_manifest,
    verify_wire_payload,
)
from torchmetrics_tpu.resilience.elastic import elastic_restore, restack_carry
from torchmetrics_tpu.resilience.faults import (
    CORRUPTION_MODES,
    EXE_FAULT_MODES,
    FaultyBackend,
    IO_FAULT_MODES,
    SimulatedCrash,
    corrupt_snapshot,
    lossy_allgather,
    perturb_replica,
    run_with_preemption,
)
from torchmetrics_tpu.resilience.quarantine import (
    attach_monitor,
    clear_quarantine,
    degradation_report,
    is_degraded,
    quarantine,
    quarantine_mask,
    quarantined_replicas,
)
from torchmetrics_tpu.resilience.snapshot import (
    SCHEMA_VERSION,
    class_fingerprint,
    restore,
    snapshot,
    validate_state_leaf,
    validate_state_pytree,
    with_snapshot_context,
)
from torchmetrics_tpu.utilities.exceptions import (
    NonFiniteStateError,
    ReplicaDivergenceError,
    StateRestoreError,
    TransientIOError,
)

__all__ = [
    "CORRUPTION_MODES",
    "DurableSnapshotStore",
    "EXE_FAULT_MODES",
    "FaultyBackend",
    "IO_FAULT_MODES",
    "LocalFSBackend",
    "NonFiniteStateError",
    "PendingSave",
    "ReplicaDivergenceError",
    "RetryPolicy",
    "SCHEMA_VERSION",
    "SimulatedCrash",
    "StateRestoreError",
    "StorageBackend",
    "TransientIOError",
    "attach_monitor",
    "build_wire_manifest",
    "class_fingerprint",
    "clear_quarantine",
    "corrupt_snapshot",
    "degradation_report",
    "elastic_restore",
    "is_degraded",
    "lossy_allgather",
    "parse_wire_manifest",
    "perturb_replica",
    "quarantine",
    "quarantine_mask",
    "quarantined_replicas",
    "replica_digest_table",
    "restack_carry",
    "restore",
    "run_with_preemption",
    "snapshot",
    "validate_state_leaf",
    "validate_state_pytree",
    "verify_replica_consistency",
    "verify_wire_payload",
    "with_snapshot_context",
]
