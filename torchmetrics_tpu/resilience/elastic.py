"""Elastic restore: resume a snapshot taken on an N-device mesh onto M devices.

Preempted jobs rarely come back on the hardware they lost — a pod slice
shrinks, a reservation grows, a host is swapped out.  *Replicated* metric
state is mesh-agnostic (every device holds the same aggregate, so a plain
:func:`~torchmetrics_tpu.resilience.snapshot.restore` broadcasts it onto any
mesh), but **per-device carries are not**: a mid-window
:class:`~torchmetrics_tpu.parallel.coalesce.SyncStepper` holds a
leading-axis-stacked ``(n_devices, *shape)`` state per device, and naively
installing an 8-row carry onto a 4-device mesh either crashes or — worse —
drops half the deferred samples.

The re-bucketing here is exact, built on the metric's own ``merge_states``:

* **Shrink (N → M, N > M):** old device ``i``'s not-yet-synced state folds
  into new device ``i % M`` — every group of rows is merged pairwise with
  the same reduction table the eventual collective would have used, so no
  sample is lost and none is double-counted.
* **Grow (N → M, M > N):** the old rows land on the first ``N`` (mod-M)
  devices and the remainder are padded with ``init_state()`` — the
  reduction identity, invisible to the eventual sync.

``elastic_restore`` is validate-before-install end to end: the restacked
carry goes through :meth:`SyncStepper.restore`'s full shape/dtype checks
before anything is touched, and failures carry the producing mesh shape in
their :class:`~torchmetrics_tpu.utilities.exceptions.StateRestoreError`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.exceptions import StateRestoreError

__all__ = ["elastic_restore", "restack_carry"]


def restack_carry(metric: Any, stacked: Mapping[str, Any], new_n: int) -> Dict[str, np.ndarray]:
    """Re-bucket one member's ``(old_n, *shape)`` stacked carry onto ``new_n``
    devices, exactly.

    Old device ``i``'s per-device state merges into new slot ``i % new_n``
    via ``metric.merge_states`` (so sums add, mins min, counters count);
    slots that receive no old device are padded with ``metric.init_state()``
    — the reduction identity.  Returns a host-numpy stacked carry with
    leading dim ``new_n``.
    """
    if new_n < 1:
        raise ValueError(f"new_n must be >= 1, got {new_n}")
    leaves = {name: np.asarray(v) for name, v in stacked.items()}
    if not leaves:
        raise StateRestoreError("cannot restack an empty carry", reason="structure")
    old_n = next(iter(leaves.values())).shape[0] if next(iter(leaves.values())).ndim else 0
    for name, arr in leaves.items():
        if arr.ndim < 1 or arr.shape[0] != old_n:
            raise StateRestoreError(
                f"carry leaf {name!r} has leading dim "
                f"{arr.shape[0] if arr.ndim else 'none'}, expected {old_n}: the stacked "
                "carry's per-device axis is inconsistent (corrupted snapshot).",
                leaf=name,
                reason="corrupt",
            )
    per_device = [
        {name: jnp.asarray(arr[i]) for name, arr in leaves.items()} for i in range(old_n)
    ]
    groups: List[List[Dict[str, Any]]] = [[] for _ in range(new_n)]
    for i, state in enumerate(per_device):
        groups[i % new_n].append(state)
    merged: List[Mapping[str, Any]] = []
    for group in groups:
        if not group:
            merged.append(metric.init_state())
            continue
        acc = group[0]
        for state in group[1:]:
            acc = metric.merge_states(acc, state)
        merged.append(acc)
    out: Dict[str, np.ndarray] = {}
    for name in leaves:
        out[name] = np.stack([np.asarray(state[name]) for state in merged])
    return out


def _restack_stepper_snapshot(stepper: Any, snap: Mapping[str, Any]) -> Dict[str, Any]:
    """A copy of a stepper snapshot with its ``local`` carry re-bucketed for
    this stepper's mesh (no-op when the device counts already agree)."""
    n = stepper._n_devices()
    local = snap.get("local")
    if local is None:
        out = dict(snap)
        out["n_devices"] = n
        return out
    if not isinstance(local, Mapping):
        raise StateRestoreError(
            f"stepper snapshot 'local' must be a mapping, got {type(local).__name__}.",
            reason="structure",
        )
    snap_n = snap.get("n_devices")
    if snap_n is None:
        # pre-elastic snapshot: infer the producing mesh from the carry itself
        for member_state in local.values():
            for leaf in member_state.values():
                snap_n = int(np.asarray(leaf).shape[0])
                break
            break
    produced = int(snap_n) if snap_n is not None else n
    if produced == n:
        out = dict(snap)
        out["n_devices"] = n
        return out
    new_local: Dict[str, Any] = {}
    for name, m in stepper._members:
        if name not in local:
            raise StateRestoreError(
                f"stepper snapshot 'local' is missing member {name!r}.",
                leaf=name,
                reason="missing-leaf",
                mesh_shape=(produced,),
            )
        new_local[name] = restack_carry(m, local[name], n)
    out = dict(snap)
    out["local"] = new_local
    out["n_devices"] = n
    return out


def elastic_restore(obj: Any, snap: Mapping[str, Any], strict_class: bool = True) -> None:
    """Restore ``snap`` into ``obj``, adapting per-device carries to the
    current mesh size.

    * For a :class:`~torchmetrics_tpu.parallel.coalesce.SyncStepper`, the
      mid-window stacked carry is re-bucketed via :func:`restack_carry` when
      the snapshot's producing mesh differs from the stepper's, then
      installed through the stepper's own validate-before-install
      :meth:`~torchmetrics_tpu.parallel.coalesce.SyncStepper.restore`.
    * For a ``Metric``/``MetricCollection``, replicated state is
      mesh-agnostic — this delegates to
      :func:`torchmetrics_tpu.resilience.restore` unchanged, regardless of
      the mesh recorded in the snapshot header.  Leaves snapshotted as
      per-shard payloads (``state_sharding`` states, spec kind
      ``"sharded"``) are reassembled to their mesh-agnostic logical array by
      that same restore, so an 8-shard snapshot restores onto a 4-device
      mesh (and back) bit-identically; the next sharded sync re-scatters the
      leaf over whatever mesh is current.
    """
    from torchmetrics_tpu.parallel.coalesce import SyncStepper

    if isinstance(obj, SyncStepper):
        if not isinstance(snap, Mapping):
            raise StateRestoreError(
                f"stepper snapshot must be a mapping, got {type(snap).__name__}.",
                reason="structure",
            )
        obj.restore(_restack_stepper_snapshot(obj, snap))
        return
    from torchmetrics_tpu.resilience.snapshot import restore

    restore(obj, snap, strict_class=strict_class)
