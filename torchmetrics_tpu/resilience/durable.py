"""Durable snapshot store: write-ahead manifests, atomic commits, retrying
I/O, and skip-back restore.

:mod:`~torchmetrics_tpu.resilience.snapshot` makes checkpoints
*self-describing*; this module makes them *durable*.  A metric snapshot that
dies with the process is only half a resilience story — the other half is
the filesystem, where real fleets see torn writes, half-written manifests,
transient NFS flakes, and full disks.  The store's contract:

* **Atomic generations.**  Every save lands in a hidden staging directory
  first: the ``MANIFEST.json`` write-ahead record (per-leaf crc32s, payload
  crc, producing mesh, schema version) is written *before* the payload, and
  the generation only becomes visible through one atomic ``rename`` to
  ``gen-NNNNNNNN``.  Readers never see a partial checkpoint — a crash at any
  point leaves either the previous generation or a committed new one, plus
  at worst an ignorable staging dir.
* **Retrying I/O.**  Every backend call runs under a :class:`RetryPolicy`:
  bounded exponential backoff with a deterministic-by-default jitter hook
  and an optional per-attempt timeout.  Errors are *classified* —
  :class:`~torchmetrics_tpu.utilities.exceptions.TransientIOError` (and
  EAGAIN-class OS errors) are retried and counted (``io_retries``);
  permanent failures (ENOSPC, EROFS, bad paths) surface immediately.
* **Skip-back restore.**  ``load()``/``restore()`` walk generations newest →
  oldest: a generation that fails its manifest, payload-crc, or per-leaf
  checksum verification is skipped with a warning (``skipbacks`` counter)
  and the next-older one is tried — a corrupt newest checkpoint degrades
  the resume point by one save interval instead of killing the run.
* **Async off the step path.**  :meth:`DurableSnapshotStore.save_async`
  copies state to host eagerly (donation-safe: the copy happens before the
  caller's next compiled step can consume its buffers) and does all
  serialization + I/O on a background thread, double-buffered — one write
  in flight plus one pending slot; a third concurrent save blocks
  (backpressure) rather than queueing unboundedly.  Nothing in the save
  path traces: armed async checkpointing adds **zero** retraces and zero
  compile-cache entries.

The storage seam (:class:`StorageBackend`) is deliberately tiny — bytes in,
bytes out, atomic rename — so object stores can slot in later and the fault
suite (:mod:`torchmetrics_tpu.resilience.faults`) can inject torn writes and
ENOSPC without touching the commit protocol.
"""

from __future__ import annotations

import errno
import json
import os
import pickle
import re
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu.observability import registry as _telemetry
from torchmetrics_tpu.resilience.snapshot import (
    restore as _restore_snapshot,
    snapshot as _take_snapshot,
    with_snapshot_context,
)
from torchmetrics_tpu.utilities.exceptions import StateRestoreError, TransientIOError
from torchmetrics_tpu.utilities.prints import rank_zero_warn

__all__ = [
    "DurableSnapshotStore",
    "LocalFSBackend",
    "MANIFEST_NAME",
    "PAYLOAD_NAME",
    "PendingSave",
    "RetryPolicy",
    "StorageBackend",
    "build_wire_manifest",
    "parse_wire_manifest",
    "verify_wire_payload",
]

MANIFEST_NAME = "MANIFEST.json"
PAYLOAD_NAME = "payload.pkl"

_MANIFEST_FORMAT = "tm-tpu-durable/1"
_GEN_RE = re.compile(r"^gen-(\d{8})$")
_STAGING_PREFIX = ".staging-"

#: OS errno values retried as transient.  ENOSPC is conspicuously absent:
#: a full disk does not heal between backoff sleeps, and retrying it only
#: delays the operator page.
_TRANSIENT_ERRNOS = frozenset(
    e
    for e in (
        errno.EAGAIN,
        getattr(errno, "EWOULDBLOCK", errno.EAGAIN),
        errno.EINTR,
        errno.EBUSY,
        errno.ETIMEDOUT,
        getattr(errno, "ESTALE", None),  # NFS handle churn
        getattr(errno, "EIO", None),
    )
    if e is not None
)


# ------------------------------------------------------------------- retry
class RetryPolicy:
    """Bounded exponential backoff with typed transient/permanent errors.

    Reused verbatim by the save and restore paths (and anything else doing
    checkpoint I/O): one classification of what is worth retrying, one
    backoff curve, one telemetry counter.

    * ``max_attempts`` — total attempts (1 = no retry).
    * ``base_delay_s`` / ``max_delay_s`` — backoff is
      ``min(max_delay_s, base_delay_s * 2**(attempt-1))``.
    * ``jitter`` — optional hook ``(delay_s, attempt) -> delay_s``.  The
      default is **no** jitter, so tests and fault drills are deterministic;
      production fleets pass e.g. a seeded ``random.uniform`` wrapper.
    * ``timeout_s`` — optional per-*attempt* wall budget; an attempt that
      exceeds it is abandoned (its worker thread is orphaned) and counts as
      a transient failure.
    * ``classify`` — optional override ``exc -> bool`` (True = transient).
      The default treats :class:`TransientIOError`, ``TimeoutError``,
      ``InterruptedError``, ``BlockingIOError`` and EAGAIN-class ``OSError``
      as transient; everything else (ENOSPC, EROFS, value errors, …) is
      permanent and raises on the first attempt.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        timeout_s: Optional[float] = None,
        jitter: Optional[Callable[[float, int], float]] = None,
        classify: Optional[Callable[[BaseException], bool]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.timeout_s = timeout_s
        self.jitter = jitter
        self.classify = classify
        self._sleep = sleep

    def is_transient(self, err: BaseException) -> bool:
        """True when ``err`` is worth retrying under this policy."""
        if self.classify is not None:
            return bool(self.classify(err))
        if isinstance(err, TransientIOError):
            return True
        if isinstance(err, (TimeoutError, InterruptedError, BlockingIOError)):
            return True
        if isinstance(err, OSError):
            return err.errno in _TRANSIENT_ERRNOS
        return False

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        delay = min(self.max_delay_s, self.base_delay_s * (2.0 ** (attempt - 1)))
        if self.jitter is not None:
            delay = float(self.jitter(delay, attempt))
        return max(0.0, delay)

    def _attempt(self, fn: Callable[[], Any]) -> Any:
        if self.timeout_s is None:
            return fn()
        box: Dict[str, Any] = {}

        def work() -> None:
            try:
                box["value"] = fn()
            except BaseException as err:  # noqa: BLE001 - re-raised on the caller thread
                box["error"] = err

        worker = threading.Thread(target=work, name="tm-tpu-io-attempt", daemon=True)
        worker.start()
        worker.join(self.timeout_s)
        if worker.is_alive():
            raise TransientIOError(
                f"I/O attempt exceeded its {self.timeout_s}s per-attempt timeout"
            )
        if "error" in box:
            raise box["error"]
        return box["value"]

    def run(self, fn: Callable[[], Any], describe: str = "io", owner: Any = None) -> Any:
        """Run ``fn`` under this policy; returns its value or raises the last
        (or first permanent) error.  Every retry bumps the ``io_retries``
        counter attributed to ``owner``."""
        for attempt in range(1, self.max_attempts + 1):
            try:
                return self._attempt(fn)
            except BaseException as err:  # noqa: BLE001 - classified below
                if not self.is_transient(err) or attempt == self.max_attempts:
                    raise
                _telemetry.count(owner, "io_retries")
                rank_zero_warn(
                    f"transient failure during {describe} (attempt {attempt}/"
                    f"{self.max_attempts}): {err!r}; retrying in {self.delay_s(attempt):.3f}s"
                )
                self._sleep(self.delay_s(attempt))
        raise AssertionError("unreachable")  # pragma: no cover


# ----------------------------------------------------------------- backends
class StorageBackend:
    """Minimal byte-level seam the durable store drives.

    Implementations must make :meth:`commit_rename` atomic (readers observe
    either no generation directory or a complete one) — everything else is
    plain bytes-in/bytes-out.  The fault-injection backends in
    :mod:`torchmetrics_tpu.resilience.faults` subclass this to reproduce
    torn writes, ENOSPC and crash-before-rename deterministically.
    """

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def commit_rename(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def remove_tree(self, path: str) -> None:
        raise NotImplementedError


class LocalFSBackend(StorageBackend):
    """Local-filesystem backend: fsync'd writes, atomic directory rename.

    ``write_bytes`` fsyncs the file before returning (the manifest must be
    durable *before* the payload starts, and both before the commit rename);
    ``commit_rename`` fsyncs the parent directory afterwards so the rename
    itself survives power loss.
    """

    def write_bytes(self, path: str, data: bytes) -> None:
        with open(path, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def commit_rename(self, src: str, dst: str) -> None:
        os.rename(src, dst)
        self._fsync_dir(os.path.dirname(dst) or ".")

    def remove_tree(self, path: str) -> None:
        if not os.path.isdir(path):
            if os.path.exists(path):
                os.remove(path)
            return
        for name in os.listdir(path):
            self.remove_tree(os.path.join(path, name))
        os.rmdir(path)

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform without dir fds
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


# ----------------------------------------------------- shared wire helpers
# The write-ahead commit protocol is payload-agnostic: a manifest records the
# payload's byte count and crc32 *before* the payload lands, and readers
# verify both before trusting a byte.  These helpers are shared between the
# snapshot store below and the executable store
# (:mod:`torchmetrics_tpu.core.warmstart`) so both payload classes ride one
# torn-write detector.
def build_wire_manifest(
    fmt: str,
    payload_name: str,
    payload: bytes,
    extra: Optional[Mapping[str, Any]] = None,
) -> bytes:
    """Serialize a write-ahead manifest for one staged payload blob."""
    manifest: Dict[str, Any] = {
        "format": fmt,
        "payload": payload_name,
        "payload_bytes": len(payload),
        "payload_crc32": zlib.crc32(payload),
    }
    if extra:
        manifest.update(extra)
    return json.dumps(manifest, indent=1, sort_keys=True).encode("utf-8")


def parse_wire_manifest(
    manifest_bytes: bytes,
    fmt: str,
    on_corrupt: Callable[[str], Exception],
    required: Tuple[str, ...] = (),
) -> Dict[str, Any]:
    """Decode + structurally validate a manifest; damage raises via
    ``on_corrupt(detail)`` (so each store keeps its own typed error).
    ``required`` names store-specific records beyond the payload checksums."""
    try:
        manifest = json.loads(manifest_bytes.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as err:
        raise on_corrupt(f"partial or garbled manifest ({err})") from err
    if not isinstance(manifest, dict) or manifest.get("format") != fmt:
        raise on_corrupt(
            f"unrecognized manifest format "
            f"{manifest.get('format') if isinstance(manifest, dict) else manifest!r}"
        )
    for key in ("payload_crc32", "payload_bytes") + tuple(required):
        if key not in manifest:
            raise on_corrupt(f"manifest is missing its {key!r} record")
    return manifest


def verify_wire_payload(
    manifest: Mapping[str, Any],
    payload: bytes,
    on_corrupt: Callable[[str], Exception],
) -> None:
    """Torn-write detection: byte count, then crc32, against the manifest."""
    if len(payload) != int(manifest["payload_bytes"]):
        raise on_corrupt(
            f"payload is {len(payload)} bytes but the manifest recorded "
            f"{manifest['payload_bytes']} (torn write)"
        )
    if zlib.crc32(payload) != int(manifest["payload_crc32"]):
        raise on_corrupt("payload crc32 does not match the manifest (torn write)")


# ------------------------------------------------------------ checksumming
def _walk_arrays(node: Any, prefix: str = "") -> Iterator[Tuple[str, np.ndarray]]:
    """Yield ``(path, host_array)`` for every array leaf in a snapshot-like
    nested structure (dict / list / tuple of numpy arrays + scalars)."""
    if isinstance(node, Mapping):
        for key in sorted(node):
            yield from _walk_arrays(node[key], f"{prefix}{key}/")
    elif isinstance(node, (list, tuple)):
        for i, item in enumerate(node):
            yield from _walk_arrays(item, f"{prefix}{i}/")
    elif isinstance(node, np.ndarray):
        yield prefix.rstrip("/"), node
    elif hasattr(node, "__array__") and not isinstance(node, (str, bytes, bool, int, float)):
        yield prefix.rstrip("/"), np.asarray(node)


def _leaf_crc(arr: np.ndarray) -> int:
    """crc32 over the leaf's identity (dtype + shape) and raw bytes."""
    arr = np.ascontiguousarray(arr)
    crc = zlib.crc32(f"{arr.dtype.str}:{arr.shape}".encode("ascii"))
    return zlib.crc32(arr.tobytes(), crc)


def _host_copy(node: Any) -> Any:
    """Deep host-numpy copy of a snapshot-like structure.

    This is the donation-safety boundary for :meth:`save_async`: every array
    leaf is materialized into a *fresh* host buffer on the caller's thread,
    so the background writer never aliases device memory the next compiled
    step may donate away.
    """
    if isinstance(node, Mapping):
        return {k: _host_copy(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return type(node)(_host_copy(v) for v in node)
    if isinstance(node, np.ndarray):
        return np.array(node, copy=True)
    if hasattr(node, "__array__") and not isinstance(node, (str, bytes, bool, int, float)):
        return np.asarray(node)  # device -> fresh host buffer
    return node


# ------------------------------------------------------------- pending save
class PendingSave:
    """Handle for one in-flight :meth:`DurableSnapshotStore.save_async`."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._generation: Optional[int] = None
        self._error: Optional[BaseException] = None

    def _finish(self, generation: Optional[int], error: Optional[BaseException]) -> None:
        self._generation = generation
        self._error = error
        self._event.set()

    def done(self) -> bool:
        """True once the background write has committed or failed."""
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> int:
        """Block until the write commits; return its generation id.

        Re-raises the background failure (already classified/retried under
        the store's :class:`RetryPolicy`) on the caller's thread — an async
        save can fail *later*, but never silently.
        """
        if not self._event.wait(timeout):
            raise TimeoutError("durable save still in flight")
        if self._error is not None:
            raise self._error
        assert self._generation is not None
        return self._generation


# -------------------------------------------------------------------- store
class DurableSnapshotStore:
    """Generational on-disk snapshot store with atomic commits.

    Layout under ``root``::

        root/
          gen-00000001/MANIFEST.json   # write-ahead record: crcs + metadata
          gen-00000001/payload.pkl     # pickled host-numpy snapshot
          gen-00000002/...
          .staging-gen-00000003/...    # in-progress write; ignored by readers

    ``save`` accepts a ``Metric``/``MetricCollection`` (snapshotted via
    :func:`torchmetrics_tpu.resilience.snapshot`) or any already-built
    snapshot-like mapping — a :meth:`SyncStepper.snapshot` carry, a
    committed autotuner policy record — so every piece of resumable state
    rides the same commit protocol.
    """

    def __init__(
        self,
        root: str,
        backend: Optional[StorageBackend] = None,
        retry: Optional[RetryPolicy] = None,
        keep_last_n: Optional[int] = None,
    ) -> None:
        if keep_last_n is not None and keep_last_n < 1:
            raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
        self.root = str(root)
        self.backend = backend if backend is not None else LocalFSBackend()
        self.retry = retry if retry is not None else RetryPolicy()
        self.keep_last_n = keep_last_n
        self._commit_lock = threading.Lock()
        self._slots = threading.BoundedSemaphore(2)  # one in flight + one pending
        self._outstanding: List[PendingSave] = []
        self._outstanding_lock = threading.Lock()
        self.retry.run(
            lambda: self.backend.makedirs(self.root), describe="store init", owner=self
        )

    # -- generation bookkeeping ------------------------------------------
    def generations(self) -> List[int]:
        """Committed generation ids, oldest first.  Staging dirs are invisible."""
        names = self.retry.run(
            lambda: self.backend.listdir(self.root), describe="list generations", owner=self
        )
        out = []
        for name in names:
            m = _GEN_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        """Newest committed generation id, or None for an empty store."""
        gens = self.generations()
        return gens[-1] if gens else None

    def _gen_dir(self, generation: int) -> str:
        return os.path.join(self.root, f"gen-{generation:08d}")

    def _staging_dir(self, generation: int) -> str:
        return os.path.join(self.root, f"{_STAGING_PREFIX}gen-{generation:08d}")

    def _next_generation(self) -> int:
        names = self.retry.run(
            lambda: self.backend.listdir(self.root), describe="list generations", owner=self
        )
        newest = 0
        for name in names:
            m = _GEN_RE.match(name) or _GEN_RE.match(name[len(_STAGING_PREFIX):] if name.startswith(_STAGING_PREFIX) else "")
            if m:
                newest = max(newest, int(m.group(1)))
        return newest + 1

    # -- save -------------------------------------------------------------
    @staticmethod
    def _as_snapshot(obj: Any, mesh_shape: Optional[Sequence[int]]) -> Mapping[str, Any]:
        # MetricCollection is itself a Mapping, so the metric/collection check
        # must come first — only genuinely raw mappings (stepper snapshots,
        # autotuner records) pass through untouched
        from torchmetrics_tpu.collections import MetricCollection
        from torchmetrics_tpu.core.metric import Metric

        if isinstance(obj, (Metric, MetricCollection)) or not isinstance(obj, Mapping):
            return _take_snapshot(obj, mesh_shape=mesh_shape)
        if mesh_shape is not None:
            snap = dict(obj)
            snap["mesh"] = [int(d) for d in mesh_shape]
            return snap
        return obj

    def _build_manifest(self, snap: Mapping[str, Any], payload: bytes, generation: int) -> bytes:
        leaves = {path: _leaf_crc(arr) for path, arr in _walk_arrays(snap)}
        return build_wire_manifest(
            _MANIFEST_FORMAT,
            PAYLOAD_NAME,
            payload,
            extra={
                "generation": generation,
                "schema_version": snap.get("schema_version"),
                "kind": snap.get("kind"),
                "class": snap.get("class"),
                "mesh": snap.get("mesh"),
                "leaves": leaves,
            },
        )

    def _write_generation(self, snap: Mapping[str, Any]) -> int:
        """The commit protocol.  Caller holds ``_commit_lock``."""
        generation = self._next_generation()
        staging = self._staging_dir(generation)
        final = self._gen_dir(generation)
        payload = pickle.dumps(dict(snap), protocol=pickle.HIGHEST_PROTOCOL)
        manifest = self._build_manifest(snap, payload, generation)
        run = self.retry.run
        run(lambda: self.backend.makedirs(staging), describe="staging mkdir", owner=self)
        # write-ahead: the manifest (with every checksum) is durable before a
        # single payload byte lands, and both are durable before the rename
        # makes the generation visible
        run(
            lambda: self.backend.write_bytes(os.path.join(staging, MANIFEST_NAME), manifest),
            describe="manifest write",
            owner=self,
        )
        run(
            lambda: self.backend.write_bytes(os.path.join(staging, PAYLOAD_NAME), payload),
            describe="payload write",
            owner=self,
        )
        run(
            lambda: self.backend.commit_rename(staging, final),
            describe="generation commit",
            owner=self,
        )
        _telemetry.count(self, "durable_saves")
        if self.keep_last_n is not None:
            self._gc_committed(self.keep_last_n)
        return generation

    def save(self, obj: Any, *, mesh_shape: Optional[Sequence[int]] = None) -> int:
        """Synchronously snapshot ``obj`` and commit a new generation."""
        snap = _host_copy(self._as_snapshot(obj, mesh_shape))
        with self._commit_lock:
            return self._write_generation(snap)

    def save_async(self, obj: Any, *, mesh_shape: Optional[Sequence[int]] = None) -> PendingSave:
        """Commit a new generation on a background thread.

        The snapshot (device→host transfer + fresh host copies) happens
        eagerly on the calling thread — after this returns, the caller may
        donate/overwrite its state buffers freely.  Serialization, checksums
        and all filesystem I/O run off the step path.  Double-buffered: with
        one write in flight and one pending, the next call blocks until a
        slot frees (bounded memory, applied backpressure — never a silent
        drop of a checkpoint).
        """
        snap = _host_copy(self._as_snapshot(obj, mesh_shape))
        self._slots.acquire()
        pending = PendingSave()
        with self._outstanding_lock:
            self._outstanding.append(pending)

        def work() -> None:
            try:
                with self._commit_lock:
                    generation = self._write_generation(snap)
                pending._finish(generation, None)
            except BaseException as err:  # noqa: BLE001 - delivered via result()
                pending._finish(None, err)
            finally:
                self._slots.release()
                with self._outstanding_lock:
                    if pending in self._outstanding:
                        self._outstanding.remove(pending)

        threading.Thread(target=work, name="tm-tpu-durable-save", daemon=True).start()
        return pending

    def wait(self, timeout: Optional[float] = None) -> None:
        """Drain every in-flight async save (re-raising the first failure)."""
        with self._outstanding_lock:
            outstanding = list(self._outstanding)
        for pending in outstanding:
            pending.result(timeout)

    # -- load / restore ---------------------------------------------------
    def _read_generation(self, generation: int) -> Dict[str, Any]:
        """Fully verify one committed generation; return its snapshot.

        Raises :class:`StateRestoreError` (reason ``"corrupt"`` / ``"io"``)
        on any damage: unreadable or partial manifest, payload length/crc
        mismatch (torn write), unpicklable payload, or a per-leaf checksum
        that no longer matches the write-ahead record.
        """
        gen_dir = self._gen_dir(generation)

        def _corrupt(detail: str, leaf: Optional[str] = None) -> StateRestoreError:
            return StateRestoreError(
                f"Durable generation {generation} failed verification: {detail}",
                leaf=leaf,
                reason="corrupt",
                generation=generation,
            )

        try:
            manifest_bytes = self.retry.run(
                lambda: self.backend.read_bytes(os.path.join(gen_dir, MANIFEST_NAME)),
                describe=f"manifest read (gen {generation})",
                owner=self,
            )
        except OSError as err:
            raise StateRestoreError(
                f"Durable generation {generation} manifest is unreadable: {err}",
                reason="io",
                generation=generation,
            ) from err
        manifest = parse_wire_manifest(
            manifest_bytes, _MANIFEST_FORMAT, _corrupt, required=("leaves",)
        )

        try:
            payload = self.retry.run(
                lambda: self.backend.read_bytes(os.path.join(gen_dir, PAYLOAD_NAME)),
                describe=f"payload read (gen {generation})",
                owner=self,
            )
        except OSError as err:
            raise StateRestoreError(
                f"Durable generation {generation} payload is unreadable: {err}",
                reason="io",
                generation=generation,
            ) from err
        verify_wire_payload(manifest, payload, _corrupt)
        try:
            snap = pickle.loads(payload)
        except Exception as err:  # noqa: BLE001 - any unpickling failure is corruption
            raise _corrupt(f"payload does not unpickle ({err})") from err
        if not isinstance(snap, Mapping):
            raise _corrupt(f"payload unpickled to {type(snap).__name__}, expected a mapping")
        recorded = manifest["leaves"]
        actual = {path: _leaf_crc(arr) for path, arr in _walk_arrays(snap)}
        for path, crc in recorded.items():
            if path not in actual:
                raise _corrupt(f"leaf {path!r} vanished from the payload", leaf=path)
            if int(actual[path]) != int(crc):
                raise _corrupt(f"leaf {path!r} checksum mismatch", leaf=path)
        extra = sorted(set(actual) - set(recorded))
        if extra:
            raise _corrupt(f"payload grew unrecorded leaf {extra[0]!r}", leaf=extra[0])
        return dict(snap)

    def load(self, generation: Optional[int] = None) -> Tuple[Dict[str, Any], int]:
        """Read a verified snapshot; returns ``(snapshot, generation)``.

        With an explicit ``generation``, that exact checkpoint is verified
        and any damage raises.  With ``generation=None`` the store walks
        newest → oldest, skipping (and warning about) corrupt generations —
        the ``skipbacks`` counter records each fallback — and raises only
        when *no* valid generation remains.
        """
        gens = self.generations()
        if generation is not None:
            if generation not in gens:
                raise StateRestoreError(
                    f"Durable generation {generation} does not exist "
                    f"(committed: {gens or 'none'}).",
                    reason="missing-generation",
                    generation=generation,
                )
            return self._read_generation(generation), generation
        if not gens:
            raise StateRestoreError(
                f"Durable store at {self.root!r} has no committed generations.",
                reason="missing-generation",
            )
        last_err: Optional[StateRestoreError] = None
        for gen in reversed(gens):
            try:
                return self._read_generation(gen), gen
            except StateRestoreError as err:
                last_err = err
                _telemetry.count(self, "skipbacks")
                rank_zero_warn(
                    f"durable generation {gen} failed verification ({err}); "
                    f"skipping back to generation {gen - 1 if gen > gens[0] else 'none'}"
                )
        raise StateRestoreError(
            f"Every committed generation in {self.root!r} failed verification "
            f"(tried {list(reversed(gens))}); last failure: {last_err}",
            reason="corrupt",
        ) from last_err

    def restore(
        self,
        obj: Any,
        generation: Optional[int] = None,
        strict_class: bool = True,
    ) -> int:
        """Load (with skip-back) and install a snapshot into ``obj``.

        Validation stays all-or-nothing (``resilience.restore``); any
        :class:`StateRestoreError` is stamped with the checkpoint's full
        identity — schema version, producing mesh shape, generation id —
        via :func:`with_snapshot_context`.  Returns the restored generation.
        """
        snap, gen = self.load(generation)
        try:
            _restore_snapshot(obj, snap, strict_class=strict_class)
        except StateRestoreError as err:
            raise with_snapshot_context(err, snap, generation=gen) from None
        _telemetry.count(obj, "durable_restores")
        return gen

    # -- retention --------------------------------------------------------
    def _gc_committed(self, keep_last_n: int) -> List[int]:
        gens = self.generations()
        doomed = gens[:-keep_last_n] if keep_last_n < len(gens) else []
        for gen in doomed:
            # Tombstone-then-delete: the doomed generation is first renamed
            # (atomically) into the `.staging-` namespace, THEN removed.  A
            # crash at any point mid-gc therefore leaves either a committed
            # generation or an orphaned staging dir the next sweep removes —
            # never a half-deleted gen-* a reader could list and fail on.
            tomb = self._staging_dir(gen)
            self.retry.run(
                lambda g=gen, t=tomb: self.backend.commit_rename(self._gen_dir(g), t),
                describe=f"gc tombstone generation {gen}",
                owner=self,
            )
            self.retry.run(
                lambda t=tomb: self.backend.remove_tree(t),
                describe=f"gc generation {gen}",
                owner=self,
            )
        return doomed

    def gc(self, keep_last_n: Optional[int] = None) -> List[int]:
        """Delete old generations (keeping the newest ``keep_last_n``) and
        sweep abandoned staging directories — both crash-before-rename
        residue and tombstones stranded by a crash *during* a previous gc
        (each sweep bumps the ``staging_sweeps`` counter).  Returns the
        deleted generation ids."""
        with self._commit_lock:
            names = self.retry.run(
                lambda: self.backend.listdir(self.root), describe="gc scan", owner=self
            )
            for name in names:
                if name.startswith(_STAGING_PREFIX):
                    self.retry.run(
                        lambda n=name: self.backend.remove_tree(os.path.join(self.root, n)),
                        describe=f"gc staging {name}",
                        owner=self,
                    )
                    _telemetry.count(self, "staging_sweeps")
            n = keep_last_n if keep_last_n is not None else self.keep_last_n
            if n is None:
                return []
            if n < 1:
                raise ValueError(f"keep_last_n must be >= 1, got {n}")
            return self._gc_committed(n)
